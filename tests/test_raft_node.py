"""Raft Node shell behavior suite.

Reference scenarios: manager/state/raft/raft_test.go:63-1025 — bootstrap,
join, replication, leader/follower failure, quorum loss & recovery, restart
from WAL, snapshot catch-up of slow/new members, member removal, leadership
transfer, ForceNewCluster — driven by the fake clock exactly like
testutils.AdvanceTicks pumps the reference's fakeclock.
"""

import os

import pytest

from swarmkit_tpu.api import Annotations, Node as ApiNode, NodeSpec
from swarmkit_tpu.encryption import SecretboxCrypter, generate_secret_key
from swarmkit_tpu.raft.node import (
    ErrCannotRemoveMember, ErrLostLeadership, NotLeaderError,
)
from swarmkit_tpu.store.by import ByName
from tests.conftest import async_test
from tests.node_harness import RaftHarness


def _obj(i):
    return ApiNode(id=f"id{i}",
                   spec=NodeSpec(annotations=Annotations(name=f"obj{i}")))


async def propose(node, i):
    await node.store.update(lambda tx: tx.create(_obj(i)))


def has_obj(node, i):
    return node.store.get("node", f"id{i}") is not None


@async_test
async def test_bootstrap_single_node():
    h = RaftHarness()
    try:
        n1 = await h.add_node()
        lead = await h.wait_for_leader()
        assert lead is n1
        await propose(n1, 1)
        assert has_obj(n1, 1)
        assert n1.get_version() >= 2
    finally:
        await h.close()


@async_test
async def test_three_node_bootstrap_and_replication():
    """raft_test.go TestRaftBootstrap + log replication."""
    h = RaftHarness()
    try:
        n1 = await h.add_node()
        await h.wait_for_leader()
        n2 = await h.add_node(join_from=n1)
        n3 = await h.add_node(join_from=n1)
        await h.wait_for_cluster()
        assert len(n1.cluster.members) == 3
        assert len(n2.cluster.members) == 3
        await propose(n1, 1)
        await h.wait_for(lambda: has_obj(n2, 1) and has_obj(n3, 1))
    finally:
        await h.close()


@async_test
async def test_leader_down_reelection_and_continued_replication():
    """raft_test.go TestRaftLeaderDown."""
    h = RaftHarness()
    try:
        n1 = await h.add_node()
        await h.wait_for_leader()
        n2 = await h.add_node(join_from=n1)
        n3 = await h.add_node(join_from=n1)
        await h.wait_for_cluster()
        await h.shutdown_node(n1)
        lead = await h.wait_for_leader()
        assert lead in (n2, n3)
        await propose(lead, 5)
        others = [n for n in (n2, n3) if n is not lead]
        await h.wait_for(lambda: all(has_obj(n, 5) for n in others))
    finally:
        await h.close()


@async_test
async def test_follower_down_majority_still_commits():
    """raft_test.go TestRaftFollowerDown."""
    h = RaftHarness()
    try:
        n1 = await h.add_node()
        await h.wait_for_leader()
        n2 = await h.add_node(join_from=n1)
        n3 = await h.add_node(join_from=n1)
        await h.wait_for_cluster()
        await h.shutdown_node(n3)
        lead = await h.wait_for_leader()
        await propose(lead, 9)
        await h.wait_for(lambda: has_obj(n1, 9) and has_obj(n2, 9))
    finally:
        await h.close()


@async_test
async def test_quorum_loss_and_recovery():
    """raft_test.go TestRaftQuorumFailure / TestRaftQuorumRecovery
    (:295/:319)."""
    h = RaftHarness()
    try:
        n1 = await h.add_node()
        await h.wait_for_leader()
        n2 = await h.add_node(join_from=n1)
        n3 = await h.add_node(join_from=n1)
        await h.wait_for_cluster()
        # cut off both followers: leader loses quorum; a proposal cannot
        # commit and fails once the (fake-clock) timeout elapses
        import asyncio
        h.network.partition({n1.addr}, {n2.addr, n3.addr})
        task = asyncio.ensure_future(propose(n1, 77))
        for _ in range(40):
            if task.done():
                break
            await h.tick()
        assert task.done(), "proposal neither committed nor timed out"
        with pytest.raises((TimeoutError, ErrLostLeadership)):
            task.result()
        assert not has_obj(n2, 77) and not has_obj(n3, 77)
        # heal: cluster recovers, can commit again
        h.network.heal()
        lead = await h.wait_for_cluster()
        await propose(lead, 88)
        await h.wait_for(lambda: all(has_obj(n, 88) for n in (n1, n2, n3)
                                     if n.running))
    finally:
        await h.close()


@async_test
async def test_follower_restart_from_wal():
    """raft_test.go TestRaftRestartNode."""
    h = RaftHarness()
    try:
        n1 = await h.add_node()
        await h.wait_for_leader()
        n2 = await h.add_node(join_from=n1)
        n3 = await h.add_node(join_from=n1)
        await h.wait_for_cluster()
        await propose(n1, 1)
        await h.wait_for(lambda: has_obj(n3, 1))
        await h.shutdown_node(n3)
        await propose(n1, 2)
        n3b = await h.restart_node(n3)
        await h.wait_for(lambda: has_obj(n3b, 1) and has_obj(n3b, 2))
        assert n3b.raft_id == n3.raft_id
    finally:
        await h.close()


@async_test
async def test_single_node_restart_preserves_state():
    h = RaftHarness()
    try:
        n1 = await h.add_node()
        await h.wait_for_leader()
        for i in range(5):
            await propose(n1, i)
        await h.shutdown_node(n1)
        n1b = await h.restart_node(n1)
        await h.wait_for_leader()
        assert all(has_obj(n1b, i) for i in range(5))
        await propose(n1b, 99)
        assert has_obj(n1b, 99)
    finally:
        await h.close()


@async_test
async def test_full_cluster_restart():
    """raft_test.go TestRaftRestartCluster (simultaneous)."""
    h = RaftHarness()
    try:
        n1 = await h.add_node()
        await h.wait_for_leader()
        n2 = await h.add_node(join_from=n1)
        n3 = await h.add_node(join_from=n1)
        await h.wait_for_cluster()
        await propose(n1, 1)
        await h.wait_for(lambda: has_obj(n2, 1) and has_obj(n3, 1))
        for n in (n1, n2, n3):
            await h.shutdown_node(n)
        nodes = [await h.restart_node(n) for n in (n1, n2, n3)]
        lead = await h.wait_for_cluster()
        assert all(has_obj(n, 1) for n in nodes)
        await propose(lead, 2)
        await h.wait_for(lambda: all(has_obj(n, 2) for n in nodes))
    finally:
        await h.close()


@async_test
async def test_new_node_catches_up_via_snapshot():
    """raft_test.go TestRaftSnapshot/NewNodeCatchUp: snapshot interval tiny,
    newcomer must receive a snapshot, not the full log."""
    h = RaftHarness()
    try:
        n1 = await h.add_node(snapshot_interval=10,
                              log_entries_for_slow_followers=2)
        await h.wait_for_leader()
        for i in range(15):
            await propose(n1, i)
        assert n1.status()["snapshot_index"] > 0
        n2 = await h.add_node(join_from=n1)
        await h.wait_for(lambda: all(has_obj(n2, i) for i in range(15)))
        # membership arrived through the snapshot too
        assert len(n2.cluster.members) == 2
    finally:
        await h.close()


@async_test
async def test_remove_member_and_blacklist():
    """raft_test.go TestRaftLeaveCluster + removed-member blacklist."""
    h = RaftHarness()
    try:
        n1 = await h.add_node()
        await h.wait_for_leader()
        n2 = await h.add_node(join_from=n1)
        n3 = await h.add_node(join_from=n1)
        await h.wait_for_cluster()
        removed_id = n3.raft_id
        await n1.remove_member(removed_id)
        await h.wait_for(lambda: len(n1.cluster.members) == 2)
        assert n1.cluster.is_id_removed(removed_id)
        # removed node notices on next contact attempt
        await h.tick(3)
        await propose(n1, 4)
        await h.wait_for(lambda: has_obj(n2, 4))
    finally:
        await h.close()


@async_test
async def test_cannot_remove_member_that_breaks_quorum():
    """reference: CanRemoveMember raft.go:1164-1190."""
    h = RaftHarness()
    try:
        n1 = await h.add_node()
        await h.wait_for_leader()
        n2 = await h.add_node(join_from=n1)
        n3 = await h.add_node(join_from=n1)
        await h.wait_for_cluster()
        # n3 down: removing n2 would leave 1/2 reachable of remaining {n1,n2}
        # → allowed (n1+n2 both reachable). Removing *n2* while n3 is down
        # leaves remaining {n1,n3} with only n1 reachable → 1 < 2 → denied.
        await h.shutdown_node(n3)
        lead = await h.wait_for_leader()
        target = n2 if lead is n1 else n1
        with pytest.raises(ErrCannotRemoveMember):
            await lead.remove_member(target.raft_id)
        # removing the DOWN node is fine
        await lead.remove_member(n3.raft_id)
        await h.wait_for(lambda: len(lead.cluster.members) == 2)
    finally:
        await h.close()


@async_test
async def test_proposal_fails_on_non_leader():
    h = RaftHarness()
    try:
        n1 = await h.add_node()
        await h.wait_for_leader()
        n2 = await h.add_node(join_from=n1)
        await h.wait_for_cluster()
        follower = n2 if n1.is_leader() else n1
        with pytest.raises(ErrLostLeadership):
            await propose(follower, 1)
    finally:
        await h.close()


@async_test
async def test_leadership_transfer():
    """reference: TransferLeadership raft.go:1222."""
    h = RaftHarness()
    try:
        n1 = await h.add_node()
        await h.wait_for_leader()
        n2 = await h.add_node(join_from=n1)
        n3 = await h.add_node(join_from=n1)
        lead = await h.wait_for_cluster()
        await lead.transfer_leadership(n2.raft_id if lead is not n2
                                       else n3.raft_id)
        await h.wait_for(lambda: h.leader() is not None
                         and h.leader() is not lead)
        newlead = h.leader()
        await propose(newlead, 3)
        await h.wait_for(lambda: all(has_obj(n, 3) for n in (n1, n2, n3)))
    finally:
        await h.close()


@async_test
async def test_force_new_cluster():
    """raft_test.go TestRaftForceNewCluster (:696): quorum permanently lost,
    operator restarts one survivor with force_new_cluster; data survives,
    membership resets to one."""
    h = RaftHarness()
    try:
        n1 = await h.add_node()
        await h.wait_for_leader()
        n2 = await h.add_node(join_from=n1)
        n3 = await h.add_node(join_from=n1)
        await h.wait_for_cluster()
        await propose(n1, 1)
        await h.wait_for(lambda: has_obj(n2, 1) and has_obj(n3, 1))
        for n in (n1, n2, n3):
            await h.shutdown_node(n)
        n1b = await h.restart_node(n1, force_new_cluster=True)
        await h.wait_for_leader()
        assert len(n1b.cluster.members) == 1
        assert has_obj(n1b, 1)
        await propose(n1b, 2)
        assert has_obj(n1b, 2)
        # cluster can grow again
        n4 = await h.add_node(join_from=n1b)
        await h.wait_for(lambda: has_obj(n4, 1) and has_obj(n4, 2))
    finally:
        await h.close()


@async_test
async def test_encrypted_wal_restart():
    """storage_test.go: WAL+snapshot encrypted at rest; restart decrypts."""
    key = generate_secret_key()
    h = RaftHarness()
    try:
        crypt = SecretboxCrypter(key)
        n1 = await h.add_node(encrypter=crypt, decrypter=crypt)
        await h.wait_for_leader()
        await propose(n1, 1)
        # raw WAL bytes must not contain the object name
        import glob
        wal_files = glob.glob(f"{n1.opts.state_dir}/raft/wal-*")
        blob = b"".join(open(f, "rb").read() for f in wal_files)
        assert b"obj1" not in blob
        await h.shutdown_node(n1)
        n1b = await h.restart_node(n1, encrypter=crypt, decrypter=crypt)
        await h.wait_for_leader()
        assert has_obj(n1b, 1)
    finally:
        await h.close()


@async_test
async def test_bare_propose_value_applies_on_leader():
    """A ProposeValue without an explicit apply callback must still apply the
    actions to the leader's own store (regression: wait.trigger suppresses
    the follower apply path for self-proposed entries)."""
    from swarmkit_tpu.api.raft_msgs import StoreAction, StoreActionKind

    h = RaftHarness()
    try:
        n1 = await h.add_node()
        await h.wait_for_leader()
        n2 = await h.add_node(join_from=n1)
        await h.wait_for_cluster()
        action = StoreAction.make(StoreActionKind.CREATE, _obj(42))
        await n1.propose_value([action])
        assert has_obj(n1, 42), "leader must apply its own bare proposal"
        await h.wait_for(lambda: has_obj(n2, 42))
    finally:
        await h.close()


@async_test
async def test_message_drop_still_converges():
    """BASELINE churn analog: 20% message loss on every edge; raft retries
    mask it."""
    h = RaftHarness()
    try:
        n1 = await h.add_node()
        await h.wait_for_leader()
        n2 = await h.add_node(join_from=n1)
        n3 = await h.add_node(join_from=n1)
        await h.wait_for_cluster()
        for a in (n1, n2, n3):
            for b in (n1, n2, n3):
                if a is not b:
                    h.network.set_drop(a.addr, b.addr, 0.2)
        lead = h.leader()
        await propose(lead, 1)
        await h.wait_for(lambda: all(has_obj(n, 1) for n in (n1, n2, n3)))
    finally:
        await h.close()


@async_test
async def test_no_pickle_on_consensus_path():
    """VERDICT r02 weak #5: WAL/snapshot payloads must be code-free —
    no pickle opcodes on disk, and a pickled (legacy) ConfChange entry
    fails loudly instead of executing on replay
    (reference: versioned-protobuf WAL, storage/walwrap.go)."""
    import glob
    import pickle
    import pickletools

    from swarmkit_tpu.raft.messages import ConfChange, ConfChangeType
    from swarmkit_tpu.raft.wire import decode_conf_change

    h = RaftHarness()
    try:
        n1 = await h.add_node(snapshot_interval=10)
        await h.wait_for_leader()
        n2 = await h.add_node(join_from=n1)  # conf change hits the WAL
        await h.wait_for_cluster()
        for i in range(12):                  # crosses a snapshot boundary
            await propose(n1, i)

        for blob_file in glob.glob(f"{n1.opts.state_dir}/raft/*"):
            blob = open(blob_file, "rb").read()
            # a pickle stream starts with PROTO (0x80) and ends with STOP
            # ('.'); scan for a parseable embedded pickle instead of just
            # magic bytes to avoid false positives on random ciphertext
            for off in range(len(blob)):
                if blob[off] != 0x80:
                    continue
                try:
                    pickletools.dis(blob[off:off + 200],
                                    out=open(os.devnull, "w"))
                except Exception:
                    continue
                raise AssertionError(
                    f"parseable pickle stream inside {blob_file}")

        # legacy pickled entry => loud failure, not deserialization
        legacy = pickle.dumps(ConfChange(id=1, type=ConfChangeType.ADD_NODE,
                                         node_id=42))
        with pytest.raises(ValueError, match="legacy/pickled"):
            decode_conf_change(legacy)
    finally:
        await h.close()


@async_test
async def test_wedged_leader_transfers_leadership():
    """reference: timedMutex/Wedged (store/memory.go:117-144,972) wired to
    TransferLeadership (raft.go:589-606): a leader whose store has a write
    stuck in flight past WEDGE_TIMEOUT hands leadership away."""
    h = RaftHarness()
    try:
        n1 = await h.add_node()
        await h.wait_for_leader()
        n2 = await h.add_node(join_from=n1)
        n3 = await h.add_node(join_from=n1)
        lead = await h.wait_for_cluster()

        # wedge the leader's store: an in-flight write whose proposal never
        # resolves (stubbed proposer future that never completes)
        class _StuckProposer:
            async def propose_value(self, actions, cb=None, timeout=1e9):
                import asyncio
                await asyncio.Event().wait()

        real = lead.store._proposer
        lead.store.set_proposer(_StuckProposer())
        import asyncio
        stuck = asyncio.ensure_future(propose(lead, 99))
        await h.pump()
        lead.store.set_proposer(real)  # later writes go through raft again
        assert lead.store._in_flight, "wedge setup failed"

        await h.tick(int(lead.store.WEDGE_TIMEOUT) + 2)
        await h.wait_for(lambda: h.leader() is not None
                         and h.leader() is not lead)
        newlead = h.leader()
        await propose(newlead, 1)
        await h.wait_for(lambda: has_obj(newlead, 1))
        stuck.cancel()
    finally:
        await h.close()


@async_test
async def test_hot_path_latency_metrics_recorded():
    """reference metric names: raft.go:69-71 propose latency,
    storage.go:20-29 snapshot latency, memory.go:81-110 store tx timers —
    recorded and queryable with percentiles."""
    from swarmkit_tpu.utils import metrics

    metrics.REGISTRY.reset()
    h = RaftHarness()
    try:
        n1 = await h.add_node(snapshot_interval=5)
        await h.wait_for_leader()
        for i in range(8):
            await propose(n1, i)
        n1.store.view(lambda v: v.find("node"))
        snap = metrics.REGISTRY.snapshot()
        assert snap[metrics.RAFT_PROPOSE_LATENCY]["count"] >= 8
        assert snap[metrics.RAFT_PROPOSE_LATENCY]["p99"] >= 0.0
        assert snap[metrics.STORE_WRITE_TX_LATENCY]["count"] >= 8
        assert snap[metrics.STORE_READ_TX_LATENCY]["count"] >= 1
        assert snap[metrics.RAFT_SNAPSHOT_LATENCY]["count"] >= 1
    finally:
        await h.close()


@async_test
async def test_join_twice_is_idempotent():
    """raft_test.go TestRaftJoinTwice: a member that re-sends its join
    (e.g. after losing the first response) keeps its raft id and the
    membership does not grow; a re-join from a NEW address updates the
    member record."""
    h = RaftHarness()
    try:
        n1 = await h.add_node()
        await h.wait_for_leader()
        n2 = await h.add_node(join_from=n1)
        await h.wait_for_cluster()
        assert len(n1.cluster.members) == 2
        rid = n2.raft_id

        # same node id, same addr: idempotent
        resp = await n1.join(n2.node_id, n2.addr)
        assert resp.raft_id == rid
        assert len(n1.cluster.members) == 2

        # same node id, NEW addr: the member record follows
        resp = await n1.join(n2.node_id, "moved:999")
        assert resp.raft_id == rid
        await h.wait_for(
            lambda: n1.cluster.members[rid].addr == "moved:999")
        assert len(n1.cluster.members) == 2
    finally:
        await h.close()


@async_test
async def test_staggered_cluster_restart():
    """raft_test.go TestRaftRestartClusterStaggered: nodes restart one at a
    time with the survivors running, preserving state and leadership
    continuity throughout."""
    h = RaftHarness()
    try:
        n1 = await h.add_node()
        await h.wait_for_leader()
        n2 = await h.add_node(join_from=n1)
        n3 = await h.add_node(join_from=n1)
        await h.wait_for_cluster()
        await propose(n1, 1)
        await h.wait_for(lambda: has_obj(n2, 1) and has_obj(n3, 1))

        nodes = {n.node_id: n for n in (n1, n2, n3)}
        for nid in list(nodes):
            await h.shutdown_node(nodes[nid])
            # quorum of 2 still serves while one node is down
            lead = await h.wait_for_leader()
            await propose(lead, 100 + int(nid.split("-")[1]))
            nodes[nid] = await h.restart_node(nodes[nid])
            await h.wait_for_cluster()
        lead = await h.wait_for_cluster()
        await propose(lead, 2)
        await h.wait_for(lambda: all(
            has_obj(n, i) for n in nodes.values()
            for i in (1, 2, 101, 102, 103)))
    finally:
        await h.close()

"""On-device telemetry plane (swarmkit_tpu/telemetry/).

Covers the ISSUE 9 acceptance criteria at tier-1 size (n=5):

- ``collect_telemetry=False`` is bit-identical to the seed behavior on all
  three wires (instant, forced mailboxes, latency+jitter) — and turning it
  ON perturbs nothing outside the ``tel_*`` side buffers;
- the device-computed propose->commit latency histogram matches a host
  oracle that replays the stamp/fold rules tick by tick (exact bucket
  agreement, two wires);
- the ring time-series decode reconstructs absolute ticks and counter
  sums; histograms compose with vmap and with the tiled log/peer passes;
- the host plane: TelemetryObs / KernelObs publish deltas-per-scrape
  (double-scrape idempotence via metrics/scrape.py), percentile edges
  agree between device and host, the Perfetto counter-track validator
  rejects malformed traces, and the DST SLO oracle bit trips/clears.

The end-to-end run -> scrape -> Perfetto export flow and the bench gate
live in slow wrappers (this file's tail and tests/test_bench_gate.py).
"""

import dataclasses
import json

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from swarmkit_tpu.flightrec import export as flight_export
from swarmkit_tpu.flightrec import record as flight_record
from swarmkit_tpu.metrics import catalog as obs_catalog
from swarmkit_tpu.metrics.registry import MetricsRegistry
from swarmkit_tpu.metrics.scrape import CounterDeltas, deltas_for
from swarmkit_tpu.raft.sim.kernel import propose
from swarmkit_tpu.raft.sim.run import KernelObs, run_ticks
from swarmkit_tpu.raft.sim.state import (
    LEADER, NONE, SimConfig, SimState, init_state,
)
from swarmkit_tpu.telemetry import (
    TelemetryObs, decode_series, percentile_edge, summarize_state,
)
from swarmkit_tpu.telemetry import series as tel

BASE = dict(n=5, log_len=64, window=8, apply_batch=16, max_props=8,
            keep=4, election_tick=10, collect_stats=True)

WIRES = {
    "instant": {},
    "mailbox": {"force_mailboxes": True},
    "latency": {"latency": 2, "latency_jitter": 1, "inflight": 2},
}


def _cfg(seed=3, **kw):
    return SimConfig(**{**BASE, **kw}, seed=seed)


def _tel_on(cfg):
    return dataclasses.replace(cfg, collect_telemetry=True,
                               telemetry_window=8, telemetry_stride=8)


@pytest.fixture(scope="module", params=[
    "instant",
    pytest.param("latency", marks=pytest.mark.slow),
    pytest.param("mailbox", marks=pytest.mark.slow),
])
def wire_pair(request):
    """(wire name, cfg off, cfg on, final off, final on): one 64-tick run
    per wire per setting, shared by every assertion in this file.  The
    instant wire stays tier-1; the mailbox/latency params ride tier-2
    with the other compile-heavy wrappers (each costs ~9 s of compile on
    the CPU box, against tier-1's tight wall budget)."""
    off = _cfg(**WIRES[request.param])
    on = _tel_on(off)
    f_off, _ = run_ticks(init_state(off), off, 64, prop_count=2)
    f_on, _ = run_ticks(init_state(on), on, 64, prop_count=2)
    return request.param, off, on, f_off, f_on


@pytest.fixture(scope="module", params=[
    pytest.param("read", marks=pytest.mark.slow)])
def read_pair(request):
    """Same shape with the read path compiled in (4th wire for identity)."""
    off = _cfg(seed=7, read_batch=4)
    on = _tel_on(off)
    f_off, _ = run_ticks(init_state(off), off, 64, prop_count=2)
    f_on, _ = run_ticks(init_state(on), on, 64, prop_count=2)
    return off, on, f_off, f_on


def _assert_identical_outside_tel(f_off, f_on):
    for f in dataclasses.fields(SimState):
        a, b = getattr(f_off, f.name), getattr(f_on, f.name)
        if f.name.startswith("tel_"):
            assert a is None, f"{f.name} must stay None when telemetry is off"
            continue
        if a is None:
            assert b is None, f.name
            continue
        assert np.array_equal(np.asarray(a), np.asarray(b)), \
            f"telemetry perturbed {f.name}"


class TestBitIdentity:
    def test_off_state_has_no_tel_fields_and_on_does_not_perturb(
            self, wire_pair):
        _, _off, _on, f_off, f_on = wire_pair
        _assert_identical_outside_tel(f_off, f_on)

    def test_read_wire(self, read_pair):
        _off, _on, f_off, f_on = read_pair
        _assert_identical_outside_tel(f_off, f_on)


class TestHistograms:
    def test_commit_histogram_counts_commits(self, wire_pair):
        name, _off, on, _f_off, f_on = wire_pair
        hist = np.asarray(f_on.tel_commit_hist)
        assert hist.sum() > 0
        assert (hist >= 0).all()
        if name == "instant":
            # same-tick propose-and-commit stamps before folding: bucket 0
            assert hist[0] == hist.sum()
        if name == "latency":
            # a 2-tick wire cannot commit in the propose tick
            assert hist[0] == 0

    def test_election_total_matches_kernel_stats(self, wire_pair):
        _name, _off, _on, _f_off, f_on = wire_pair
        won = int(np.asarray(f_on.stats)[1])
        assert int(np.asarray(f_on.tel_elect_hist).sum()) == won > 0

    def test_read_histogram_settles_batches(self, read_pair):
        _off, on, _f_off, f_on = read_pair
        hist = np.asarray(f_on.tel_read_hist)
        assert 0 < hist.sum() <= 64 * on.n

    def test_device_and_host_percentiles_agree(self, wire_pair):
        _name, _off, _on, _f_off, f_on = wire_pair
        counts = np.asarray(f_on.tel_commit_hist)
        for q in (50, 99):
            dev = int(tel.percentile_edge_device(f_on.tel_commit_hist, q))
            assert dev == percentile_edge(counts, q)


class TestCommitLatencyOracle:
    """Device histogram == host replay of the stamp/fold rules."""

    @pytest.mark.parametrize("wire_kw", [
        {},
        pytest.param({"latency": 2, "latency_jitter": 1, "inflight": 2},
                     marks=pytest.mark.slow)],
        ids=["instant", "latency"])
    def test_exact_bucket_agreement(self, wire_kw):
        props = 2
        cfg = _tel_on(_cfg(seed=5, **wire_kw))
        state = init_state(cfg)
        stamps: dict = {}
        hist = np.zeros(tel.NUM_BUCKETS, np.int64)
        for _ in range(70):
            pre_role = np.asarray(state.role)
            pre_last = np.asarray(state.last)
            pre_snap = np.asarray(state.snap_idx)
            pre_commit = np.asarray(state.commit)
            pre_tx = np.asarray(state.transferee)
            memb = np.asarray(jnp.diagonal(state.member))
            tick = int(state.tick)
            state, _ = run_ticks(state, cfg, 1, prop_count=props)
            post_role = np.asarray(state.role)
            post_commit = np.asarray(state.commit)
            for r in range(cfg.n):
                # _leader_ok mirror on the pre-tick state
                room = pre_last[r] + cfg.max_props - pre_snap[r] <= cfg.log_len
                if pre_role[r] == LEADER and memb[r] and room \
                        and pre_tx[r] == NONE:
                    for idx in range(pre_last[r] + 1, pre_last[r] + 1 + props):
                        stamps[(r, idx)] = tick
                # Phase D fold mirror: only leader rows fold, over this
                # tick's (commit_pre, commit_post] advance
                if post_role[r] == LEADER and post_commit[r] > pre_commit[r]:
                    for idx in range(pre_commit[r] + 1, post_commit[r] + 1):
                        t0 = stamps.get((r, idx))
                        if t0 is not None:
                            lat = tick - t0
                            b = sum(lat > e
                                    for e in tel.LATENCY_BUCKET_EDGES)
                            hist[b] += 1
                # step-down wipe mirror: a row not leading after this
                # tick drops all its batch records (its uncommitted
                # entries may be truncated; a later leadership at the
                # same indexes must not fold another term's stamps)
                if post_role[r] != LEADER:
                    for k in [k for k in stamps if k[0] == r]:
                        del stamps[k]
        assert hist.sum() > 0
        np.testing.assert_array_equal(
            np.asarray(state.tel_commit_hist), hist)


class TestSeriesRing:
    def test_decode_reconstructs_ticks_and_sums(self, wire_pair):
        name, _off, on, _f_off, f_on = wire_pair
        if name != "instant":
            pytest.skip("one wire is enough for the decoder")
        out = decode_series(f_on, on)
        assert sorted(out) == sorted(tel.SERIES_NAMES.values())
        for pts in out.values():
            ticks = [t for t, _ in pts]
            assert ticks == sorted(ticks)
            assert all(t % on.telemetry_stride == 0 for t in ticks)
        # 64 ticks == window(8) x stride(8): every commit is still in the
        # ring, so the counter-row sum equals the total committed
        assert sum(v for _, v in out["commit_rate"]) \
            == int(np.asarray(f_on.commit).sum())
        # gauge row: last point is the final tick's occupancy snapshot
        assert out["log_occupancy"][-1][1] \
            == int((np.asarray(f_on.last) - np.asarray(f_on.snap_idx)).sum())

    def test_decode_on_fresh_state_is_empty(self):
        cfg = _tel_on(_cfg())
        out = decode_series(init_state(cfg), cfg)
        assert all(pts == [] for pts in out.values())

    def test_ring_write_gauge_vs_counter_rows(self):
        series = jnp.zeros((tel.NUM_SERIES, 4), jnp.int32)
        vals = jnp.full((tel.NUM_SERIES,), 3, jnp.int32)
        s = tel.ring_write(series, 2, jnp.asarray(0, jnp.int32), vals)
        s = tel.ring_write(s, 2, jnp.asarray(1, jnp.int32), vals)
        col = np.asarray(s)[:, 0]
        # counter rows accumulate within the stride bucket, gauges overwrite
        for i in range(tel.NUM_SERIES):
            assert col[i] == (3 if i in tel.GAUGE_ROWS else 6)


@pytest.mark.slow
class TestCompose:
    def test_vmap_matches_individual_runs(self):
        cfg = _tel_on(_cfg(seed=0))
        seeds = (0, 1)
        inits = [init_state(dataclasses.replace(cfg, seed=s)) for s in seeds]
        singles = [run_ticks(st, cfg, 32, prop_count=1)[0] for st in inits]
        stacked = jax.tree.map(lambda *xs: jnp.stack(xs), *inits)
        finals, _ = jax.vmap(
            lambda st: run_ticks(st, cfg, 32, prop_count=1))(stacked)
        assert finals.tel_commit_hist.shape == (2, tel.NUM_BUCKETS)
        for i in range(len(seeds)):
            for fname in ("tel_commit_hist", "tel_elect_hist", "tel_series"):
                np.testing.assert_array_equal(
                    np.asarray(getattr(finals, fname))[i],
                    np.asarray(getattr(singles[i], fname)), err_msg=fname)

    def test_tiled_log_pass_matches_untiled(self):
        base = dict(n=5, log_len=512, window=8, apply_batch=16, max_props=8,
                    keep=4, election_tick=10, seed=2, collect_telemetry=True)
        un = SimConfig(**base, log_chunk=0)
        ti = SimConfig(**base, log_chunk=128)
        assert ti.tiled and not un.tiled
        f_un, _ = run_ticks(init_state(un), un, 48, prop_count=2)
        f_ti, _ = run_ticks(init_state(ti), ti, 48, prop_count=2)
        assert int(np.asarray(f_un.tel_commit_hist).sum()) > 0
        for fname in ("tel_commit_hist", "tel_elect_hist", "tel_read_hist",
                      "tel_series"):
            np.testing.assert_array_equal(
                np.asarray(getattr(f_un, fname)),
                np.asarray(getattr(f_ti, fname)), err_msg=fname)

    def test_banded_peer_pass_matches_dense(self):
        base = dict(n=16, log_len=64, window=8, apply_batch=16, max_props=8,
                    keep=4, election_tick=10, seed=4, collect_telemetry=True)
        dense = SimConfig(**base, peer_chunk=0)
        banded = SimConfig(**base, peer_chunk=8)
        assert banded.peer_tiled and not dense.peer_tiled
        f_d, _ = run_ticks(init_state(dense), dense, 40, prop_count=2)
        f_b, _ = run_ticks(init_state(banded), banded, 40, prop_count=2)
        assert int(np.asarray(f_d.tel_commit_hist).sum()) > 0
        for fname in ("tel_commit_hist", "tel_elect_hist", "tel_series"):
            np.testing.assert_array_equal(
                np.asarray(getattr(f_d, fname)),
                np.asarray(getattr(f_b, fname)), err_msg=fname)


class TestHostApiStamps:
    def test_propose_stamps_batch_record(self):
        cfg = _tel_on(_cfg(seed=3))
        st = init_state(cfg)
        st = dataclasses.replace(st, role=st.role.at[0].set(LEADER))
        payloads = jnp.arange(cfg.max_props, dtype=jnp.uint32)
        st2 = propose(st, cfg, payloads, 2)
        bs = int(st.tick) % tel.PROP_RING
        bidx = np.asarray(st2.tel_prop_idx)
        bcnt = np.asarray(st2.tel_prop_cnt)
        btick = np.asarray(st2.tel_prop_tick)
        assert bidx[0, bs] == int(st.last[0]) + 1
        assert bcnt[0, bs] == 2
        assert btick[0, bs] == int(st.tick)
        # non-proposing rows get this tick's column cleared, not stamped
        assert (bidx[1:, bs] == NONE).all() and (bcnt[1:, bs] == 0).all()
        # the rest of the ring is untouched
        other = np.ones(tel.PROP_RING, bool)
        other[bs] = False
        assert (bidx[:, other] == NONE).all()


class TestObsPublishers:
    def test_counter_deltas_unit(self):
        d = CounterDeltas()
        assert d.advance(("a",), 5) == 5
        assert d.advance(("a",), 5) == 0
        assert d.advance(("a",), 9) == 4
        # device counter reset (new run): re-baseline, count the reading
        assert d.advance(("a",), 3) == 3
        assert d.advance(("b",), 2) == 2

    def test_deltas_for_is_per_registry(self):
        r1, r2 = MetricsRegistry(), MetricsRegistry()
        assert deltas_for(r1) is deltas_for(r1)
        assert deltas_for(r1) is not deltas_for(r2)

    def test_telemetry_obs_double_scrape_is_idempotent(self, wire_pair):
        name, _off, on, _f_off, f_on = wire_pair
        if name != "instant":
            pytest.skip("registry behavior is wire-independent")
        reg = MetricsRegistry()
        obs = TelemetryObs(registry=reg)
        s1 = obs.publish(f_on, on)
        s2 = obs.publish(f_on, on)
        assert s1["commit"]["total"] == s2["commit"]["total"] > 0
        fam = obs_catalog.get(reg, "swarm_telemetry_commit_latency_ticks")
        child = fam._default()
        np.testing.assert_array_equal(
            np.asarray(child.counts), np.asarray(f_on.tel_commit_hist))
        assert child.count == int(np.asarray(f_on.tel_commit_hist).sum())

    def test_kernel_obs_double_scrape_is_idempotent(self, read_pair):
        _off, on, _f_off, f_on = read_pair
        reg = MetricsRegistry()
        obs = KernelObs(obs=reg)
        out1 = obs.publish(f_on)
        out2 = obs.publish(f_on)
        assert out1 == out2 and out1["reads_served"] > 0
        served = obs_catalog.get(reg, "swarm_kernel_reads_served_total")
        assert served.value == out1["reads_served"]
        commits = obs_catalog.get(reg, "swarm_kernel_commit_advance_total")
        assert commits.value == out1["commit_advance"]

    def test_two_kernel_obs_share_one_registry_table(self, read_pair):
        # the historical bug: two publishers over one registry each kept a
        # private last-seen table, so the second re-added the cumulative
        _off, on, _f_off, f_on = read_pair
        reg = MetricsRegistry()
        out = KernelObs(obs=reg).publish(f_on)
        KernelObs(obs=reg).publish(f_on)
        served = obs_catalog.get(reg, "swarm_kernel_reads_served_total")
        assert served.value == out["reads_served"]

    def test_summarize_state_disabled(self):
        cfg = _cfg()
        assert summarize_state(init_state(cfg), cfg) == {"enabled": False}


class TestPercentiles:
    def test_host_percentile_edges(self):
        counts = np.zeros(tel.NUM_BUCKETS, int)
        assert percentile_edge(counts, 99) is None
        counts[0] = 99
        counts[3] = 1
        assert percentile_edge(counts, 50) == tel.LATENCY_BUCKET_EDGES[0]
        assert percentile_edge(counts, 99) == tel.LATENCY_BUCKET_EDGES[0]
        assert percentile_edge(counts, 100) == tel.LATENCY_BUCKET_EDGES[3]
        # overflow bucket clamps to the largest finite edge (JSON-safe)
        over = np.zeros(tel.NUM_BUCKETS, int)
        over[-1] = 10
        assert percentile_edge(over, 50) == tel.LATENCY_BUCKET_EDGES[-1]

    def test_device_overflow_reads_as_int32_max(self):
        hist = jnp.zeros((tel.NUM_BUCKETS,), jnp.int32).at[-1].set(5)
        assert int(tel.percentile_edge_device(hist, 99)) \
            == np.iinfo(np.int32).max

    def test_bucket_of_is_total(self):
        lats = jnp.asarray([0, 1, 2, 255, 256, 257, 100000], jnp.int32)
        got = np.asarray(tel.bucket_of(lats))
        np.testing.assert_array_equal(got, [0, 0, 1, 8, 8, 9, 9])


class TestSloOracle:
    def test_bit_trips_and_clears(self, wire_pair):
        from swarmkit_tpu.dst.invariants import SLO_COMMIT_P99, check_state
        name, _off, on, _f_off, f_on = wire_pair
        if name != "latency":
            pytest.skip("needs a wire with p99 > 1 tick")
        tight = dataclasses.replace(on, slo_p99_commit_ticks=1)
        loose = dataclasses.replace(on, slo_p99_commit_ticks=1 << 20)
        assert int(check_state(f_on, tight)) & SLO_COMMIT_P99
        assert not int(check_state(f_on, loose)) & SLO_COMMIT_P99
        # empty histogram (no commits yet): bound set, bit clear
        assert not int(check_state(init_state(tight), tight)) & SLO_COMMIT_P99

    def test_bound_requires_telemetry(self):
        with pytest.raises(ValueError):
            _cfg(slo_p99_commit_ticks=5)


class TestConfigValidation:
    def test_window_and_stride_bounds(self):
        with pytest.raises(ValueError):
            _cfg(collect_telemetry=True, telemetry_window=4)
        with pytest.raises(ValueError):
            _cfg(collect_telemetry=True, telemetry_stride=0)
        _cfg(collect_telemetry=True)  # defaults are valid


class TestCounterTrackValidator:
    def _trace(self, events):
        return {"traceEvents": events}

    def _c(self, name, ts, value=1.0, tid=0):
        return {"ph": "C", "pid": 1, "tid": tid, "ts": ts, "name": name,
                "args": {"value": value}}

    def test_valid_counter_track_passes(self):
        t = self._trace([self._c("a", 0), self._c("a", 1), self._c("b", 0)])
        assert flight_export.validate_chrome_trace(t) == []

    def test_backwards_timestamps_fail(self):
        t = self._trace([self._c("a", 5), self._c("a", 3)])
        assert any("backwards" in p
                   for p in flight_export.validate_chrome_trace(t))

    def test_split_tid_fails(self):
        t = self._trace([self._c("a", 0, tid=0), self._c("a", 1, tid=1)])
        assert any("one track per series" in p
                   for p in flight_export.validate_chrome_trace(t))

    def test_non_numeric_value_fails(self):
        bad = [self._c("a", 0, value="high"), self._c("b", 0, value=True)]
        problems = flight_export.validate_chrome_trace(self._trace(bad))
        assert sum("non-numeric" in p for p in problems) == 2

    def test_missing_ts_fails(self):
        e = {"ph": "C", "pid": 1, "tid": 0, "name": "a",
             "args": {"value": 1}}
        assert any("lacks numeric ts" in p
                   for p in flight_export.validate_chrome_trace(
                       self._trace([e])))

    def test_counter_events_sorted_per_track(self, wire_pair):
        name, _off, on, _f_off, f_on = wire_pair
        if name != "instant":
            pytest.skip("one wire is enough for the exporter")
        counters = [{"name": sname, "tick": t, "value": v}
                    for sname, pts in decode_series(f_on, on).items()
                    for t, v in pts]
        trace = flight_export.to_chrome_trace((), (), counters=counters)
        assert flight_export.validate_chrome_trace(trace) == []
        cs = [e for e in trace["traceEvents"] if e["ph"] == "C"]
        assert len(cs) == len(counters) > 0
        assert all(e["name"].startswith("telemetry.") for e in cs)


@pytest.mark.slow
def test_telemetry_end_to_end(tmp_path, capsys):
    """Full loop: recorded+telemetry run -> TelemetryObs scrape -> flight
    record with counter tracks -> flight_view export --check (merged
    flight+telemetry trace is schema-valid)."""
    from tools.flight_view import main as flight_view_main

    cfg = dataclasses.replace(_tel_on(_cfg(seed=11)),
                              record_events=True, event_ring=128)
    final, _ = run_ticks(init_state(cfg), cfg, 80, prop_count=2)

    summary = TelemetryObs(registry=MetricsRegistry()).publish(final, cfg)
    assert summary["enabled"] and summary["commit"]["total"] > 0
    assert summary["commit"]["p99"] is not None

    rec = flight_record.capture(final, trigger="manual", cfg=cfg,
                                meta={"seed": 11})
    path = tmp_path / "rec.json"
    flight_record.save_record(rec, str(path))
    loaded = flight_record.load_record(str(path))
    assert loaded.counters == rec.counters and rec.counters

    trace_path = tmp_path / "rec.trace.json"
    assert flight_view_main(["export", str(path), "-o", str(trace_path),
                             "--check"]) == 0
    trace = json.loads(trace_path.read_text())
    phases = {t["ph"] for t in trace["traceEvents"]}
    assert {"i", "C"} <= phases, "merged flight + telemetry trace"
    assert flight_export.validate_chrome_trace(trace) == []

    assert flight_view_main(["summarize", str(path)]) == 0
    out = capsys.readouterr().out
    assert "telemetry counters" in out

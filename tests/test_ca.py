"""CA/security suite (reference: ca/certificates_test.go, ca/server_test.go,
ca/config_test.go, ca/keyreadwriter_test.go)."""

import asyncio
import os
import tempfile

import pytest

from swarmkit_tpu.api import (
    Annotations, Cluster, ClusterSpec, NodeRole,
)
from swarmkit_tpu.ca import (
    CAServer, CertificateError, InvalidJoinToken, KeyReadWriter,
    MANAGER_ROLE_OU, WORKER_ROLE_OU, RootCA, SecurityConfig, TLSRenewer,
    authorize_org_and_role, create_csr, generate_join_token, parse_identity,
    parse_join_token, PermissionDenied,
)
from swarmkit_tpu.api.types import IssuanceState
from swarmkit_tpu.store.memory import MemoryStore
from swarmkit_tpu.utils.clock import FakeClock
from tests.conftest import async_test, requires_cryptography


@requires_cryptography
def test_root_ca_create_and_issue():
    root = RootCA.create()
    assert root.can_sign
    issued = root.issue_node_certificate("node1", WORKER_ROLE_OU, "org1")
    assert issued.key_pem is not None
    node_id, role, org = parse_identity(issued.cert_pem)
    assert (node_id, role, org) == ("node1", WORKER_ROLE_OU, "org1")
    root.validate_cert_chain(issued.cert_pem)

    # a cert from a different CA is rejected
    other = RootCA.create()
    foreign = other.issue_node_certificate("evil", WORKER_ROLE_OU, "org1")
    with pytest.raises(CertificateError):
        root.validate_cert_chain(foreign.cert_pem)


@requires_cryptography
def test_csr_signing_round_trip():
    root = RootCA.create()
    csr_pem, key_pem = create_csr("node9")
    issued = root.issue_node_certificate("node9", MANAGER_ROLE_OU, "orgX",
                                         csr_pem=csr_pem)
    assert issued.key_pem is None  # key stays with the requester
    root.validate_cert_chain(issued.cert_pem)
    assert parse_identity(issued.cert_pem)[0] == "node9"


@requires_cryptography
def test_join_token_format_and_parse():
    root = RootCA.create()
    token = generate_join_token(root)
    parsed = parse_join_token(token)
    assert parsed.version == 1
    assert parsed.ca_digest == root.digest()
    with pytest.raises(InvalidJoinToken):
        parse_join_token("SWMTKN-2-x-y")
    with pytest.raises(InvalidJoinToken):
        parse_join_token("garbage")


@requires_cryptography
def test_authorization():
    root = RootCA.create()
    mgr = root.issue_node_certificate("m1", MANAGER_ROLE_OU, "org1")
    wrk = root.issue_node_certificate("w1", WORKER_ROLE_OU, "org1")
    info = authorize_org_and_role(mgr.cert_pem, root, "org1",
                                  MANAGER_ROLE_OU)
    assert info.node_id == "m1"
    with pytest.raises(PermissionDenied):   # worker can't act as manager
        authorize_org_and_role(wrk.cert_pem, root, "org1", MANAGER_ROLE_OU)
    with pytest.raises(PermissionDenied):   # wrong org
        authorize_org_and_role(mgr.cert_pem, root, "org2", MANAGER_ROLE_OU)


def test_keyreadwriter_kek_lock():
    tmp = tempfile.TemporaryDirectory()
    krw = KeyReadWriter(tmp.name, kek=b"passw0rd")
    krw.write(b"CERT", b"KEY")
    # raw file must not contain the plaintext key
    raw = open(krw.key_path, "rb").read()
    assert b"KEY" not in raw
    cert, key = krw.read()
    assert (cert, key) == (b"CERT", b"KEY")

    # without the kek the key is locked
    locked = KeyReadWriter(tmp.name)
    with pytest.raises(PermissionError):
        locked.read()
    with pytest.raises(PermissionError):
        KeyReadWriter(tmp.name, kek=b"wrong").read()

    # kek rotation to unencrypted
    krw.set_kek(None)
    cert, key = KeyReadWriter(tmp.name).read()
    assert key == b"KEY"


@async_test
@requires_cryptography
async def test_ca_server_token_join_and_renewal():
    clock = FakeClock()
    store = MemoryStore(clock=clock.now)
    root = RootCA.create()
    cluster = Cluster(id="org1", spec=ClusterSpec(
        annotations=Annotations(name="default")))
    cluster.root_ca.ca_cert = root.cert_pem
    cluster.root_ca.join_token_worker = generate_join_token(root)
    cluster.root_ca.join_token_manager = generate_join_token(root)
    await store.update(lambda tx: tx.create(cluster))
    ca = CAServer(store, root, org="org1", clock=clock)

    # worker token -> worker role + node record
    csr, key = create_csr()
    node_id, issued = await ca.issue_node_certificate(
        csr, cluster.root_ca.join_token_worker, addr="1.2.3.4")
    node = store.get("node", node_id)
    assert node.role == NodeRole.WORKER
    assert parse_identity(issued.cert_pem)[1] == WORKER_ROLE_OU
    state, cert = ca.node_certificate_status(node_id)
    assert state == IssuanceState.ISSUED and cert == issued.cert_pem

    # manager token -> manager role
    csr2, _ = create_csr()
    m_id, m_issued = await ca.issue_node_certificate(
        csr2, cluster.root_ca.join_token_manager)
    assert store.get("node", m_id).role == NodeRole.MANAGER

    # garbage and foreign tokens rejected
    with pytest.raises(InvalidJoinToken):
        await ca.issue_node_certificate(csr, "SWMTKN-1-beef-dead")
    foreign = generate_join_token(RootCA.create())
    with pytest.raises(InvalidJoinToken):
        await ca.issue_node_certificate(csr, foreign)

    # renewal follows desired_role (promotion via cert renewal); the CSR
    # must prove possession of the certificate's key
    from swarmkit_tpu.ca import create_csr_from_key

    def promote(tx):
        n = tx.get("node", node_id).copy()
        n.spec.desired_role = NodeRole.MANAGER
        tx.update(n)
    await store.update(promote)
    renew_csr = create_csr_from_key(key, node_id)
    renewed = await ca.renew_node_certificate(node_id, issued.cert_pem,
                                              renew_csr)
    assert parse_identity(renewed.cert_pem)[1] == MANAGER_ROLE_OU
    assert store.get("node", node_id).role == NodeRole.MANAGER

    # a CSR over a DIFFERENT key is rejected (identity theft guard)
    evil_csr, _ = create_csr(node_id)
    with pytest.raises(CertificateError):
        await ca.renew_node_certificate(node_id, renewed.cert_pem, evil_csr)


@async_test
@requires_cryptography
async def test_security_config_role_change_event():
    root = RootCA.create()
    issued = root.issue_node_certificate("n1", WORKER_ROLE_OU, "org1")
    sec = SecurityConfig(root, "n1", WORKER_ROLE_OU, "org1",
                         issued.cert_pem, issued.key_pem)
    watcher = sec.updates.watch()
    promoted = root.issue_node_certificate("n1", MANAGER_ROLE_OU, "org1")
    sec.update_cert(promoted.cert_pem, promoted.key_pem)
    assert sec.is_manager
    ev = watcher.try_get()
    assert ev is not None and ev.role == MANAGER_ROLE_OU

"""Small manager services: keymanager, role manager, watch API, log broker,
metrics, resource API.

Reference scenarios: manager/keymanager/keymanager_test.go,
manager/role_manager_test.go, manager/watchapi/watch_test.go,
manager/logbroker/broker_test.go.
"""

import asyncio

import pytest

from swarmkit_tpu.api import (
    Annotations, Cluster, ClusterSpec, Network, NetworkSpec, Node, NodeRole,
    NodeSpec, NodeState, Task, TaskSpec, TaskState, TaskStatus,
)
from swarmkit_tpu.api.objects import NodeStatus
from swarmkit_tpu.manager.keymanager import KeyManager, KEYRING_SIZE
from swarmkit_tpu.manager.logbroker import (
    LogBroker, LogMessage, LogSelector, LogStream,
)
from swarmkit_tpu.manager.metrics import Collector
from swarmkit_tpu.manager.resourceapi import ResourceApi, ResourceError
from swarmkit_tpu.manager.watchapi import WatchSelector, WatchServer
from swarmkit_tpu.store.memory import MemoryStore
from swarmkit_tpu.utils.clock import FakeClock
from tests.conftest import async_test


async def pump(steps=10):
    for _ in range(steps):
        await asyncio.sleep(0)


@async_test
async def test_keymanager_seeds_and_rotates():
    clock = FakeClock()
    store = MemoryStore(clock=clock.now)
    await store.update(lambda tx: tx.create(Cluster(
        id="c1", spec=ClusterSpec(annotations=Annotations(name="default")))))
    km = KeyManager(store, clock=clock, rotation_interval=10.0)
    await km.start()
    cl = store.get("cluster", "c1")
    subsystems = {k.subsystem for k in cl.network_bootstrap_keys}
    assert subsystems == {"networking:gossip", "networking:ipsec"}
    lamport0 = cl.encryption_key_lamport_clock

    # rotation adds new primaries and trims the ring
    for _ in range(4):
        await clock.advance(10.0)
        await pump()
    cl = store.get("cluster", "c1")
    assert cl.encryption_key_lamport_clock > lamport0
    per_subsys = {}
    for k in cl.network_bootstrap_keys:
        per_subsys.setdefault(k.subsystem, []).append(k)
    for ring in per_subsys.values():
        assert len(ring) <= KEYRING_SIZE
    await km.stop()


@async_test
async def test_role_manager_promote_and_demote():
    from swarmkit_tpu.manager.role_manager import RoleManager

    class FakeMember:
        def __init__(self, raft_id, node_id):
            self.raft_id, self.node_id, self.addr = raft_id, node_id, ""

    class FakeRaft:
        def __init__(self):
            self.raft_id = 1
            self.removed = []
            self.cluster = type("C", (), {})()
            self.cluster.members = {1: FakeMember(1, "n1"),
                                    2: FakeMember(2, "n2")}

        def is_leader(self):
            return True

        def can_remove_member(self, raft_id):
            return True

        async def remove_member(self, raft_id):
            self.removed.append(raft_id)
            self.cluster.members.pop(raft_id, None)

        async def transfer_leadership(self):
            raise RuntimeError("no transfer in test")

    clock = FakeClock()
    store = MemoryStore(clock=clock.now)
    raft = FakeRaft()
    mk = lambda i, role, desired: Node(
        id=f"n{i}", spec=NodeSpec(annotations=Annotations(name=f"n{i}"),
                                  desired_role=desired),
        role=role, status=NodeStatus(state=NodeState.READY))
    await store.update(lambda tx: [
        tx.create(mk(1, NodeRole.MANAGER, NodeRole.MANAGER)),
        tx.create(mk(2, NodeRole.MANAGER, NodeRole.MANAGER)),
        tx.create(mk(3, NodeRole.WORKER, NodeRole.WORKER)),
    ])
    rm = RoleManager(store, raft, clock=clock)
    await rm.start()
    await pump()

    # promote n3
    def promote(tx):
        n = tx.get("node", "n3").copy()
        n.spec.desired_role = NodeRole.MANAGER
        tx.update(n)
    await store.update(promote)
    await clock.advance(17.0)
    await pump()
    assert store.get("node", "n3").role == NodeRole.MANAGER

    # demote n2: first pass removes the raft member, next flips the role
    def demote(tx):
        n = tx.get("node", "n2").copy()
        n.spec.desired_role = NodeRole.WORKER
        tx.update(n)
    await store.update(demote)
    for _ in range(3):
        await clock.advance(17.0)
        await pump()
    assert raft.removed == [2]
    assert store.get("node", "n2").role == NodeRole.WORKER
    await rm.stop()


@async_test
async def test_watchapi_filters_and_versions():
    clock = FakeClock()
    store = MemoryStore(clock=clock.now)
    ws = WatchServer(store)
    got = []

    async def consume():
        async for m in ws.watch([WatchSelector(kind="task")],
                                include_old_object=True):
            got.append(m)

    c = asyncio.get_running_loop().create_task(consume())
    await pump()
    await store.update(lambda tx: tx.create(Task(
        id="t1", spec=TaskSpec(), status=TaskStatus())))
    await store.update(lambda tx: tx.create(Node(
        id="n1", spec=NodeSpec(annotations=Annotations(name="n1")))))

    def upd(tx):
        t = tx.get("task", "t1").copy()
        t.status.state = TaskState.RUNNING
        tx.update(t)
    await store.update(upd)
    await pump()
    assert [(m.action, m.kind) for m in got] == [
        ("create", "task"), ("update", "task")]
    assert got[1].old_object.status.state == TaskState.NEW
    assert got[1].version > got[0].version > 0
    c.cancel()


@async_test
async def test_logbroker_round_trip():
    store = MemoryStore()
    await store.update(lambda tx: [
        tx.create(Task(id="t1", node_id="n1", service_id="svc1",
                       spec=TaskSpec(),
                       status=TaskStatus(state=TaskState.RUNNING))),
    ])
    lb = LogBroker(store)

    client_msgs = []

    async def client():
        async for m in lb.subscribe_logs(LogSelector(service_ids=["svc1"])):
            client_msgs.append(m)
            if len(client_msgs) >= 2:
                return

    agent_subs = []

    async def agent():
        async for sub in lb.listen_subscriptions("n1"):
            if sub.close:
                continue
            agent_subs.append(sub)
            await lb.publish_logs(sub.id, [
                LogMessage(stream=LogStream.STDOUT, data=b"hello"),
                LogMessage(stream=LogStream.STDERR, data=b"world"),
            ])

    loop = asyncio.get_running_loop()
    at = loop.create_task(agent())
    await pump()
    ct = loop.create_task(client())
    await asyncio.wait_for(ct, timeout=5)
    assert [m.data for m in client_msgs] == [b"hello", b"world"]
    assert len(agent_subs) == 1
    at.cancel()


@async_test
async def test_metrics_collector_counts():
    store = MemoryStore()
    coll = Collector(store)
    await coll.start()
    await store.update(lambda tx: [
        tx.create(Node(id="n1", spec=NodeSpec(
            annotations=Annotations(name="n1")),
            status=NodeStatus(state=NodeState.READY))),
        tx.create(Task(id="t1", spec=TaskSpec(),
                       status=TaskStatus(state=TaskState.RUNNING))),
    ])
    await pump()
    snap = coll.snapshot()
    assert snap["swarm_node_ready"] == 1
    assert snap["swarm_task_running"] == 1
    coll.set_leader(True)
    assert coll.snapshot()["swarm_manager_leader"] == 1.0
    await coll.stop()


@async_test
async def test_metrics_collector_incremental_matches_recount():
    """Gauges track create/update/remove incrementally (O(1) per event —
    a recount per commit deep-copied the whole store and dominated
    proposal latency) and resync after a bulk restore, always matching a
    fresh full recount."""
    store = MemoryStore()
    coll = Collector(store)
    await coll.start()

    def mk_task(i, state):
        return Task(id=f"t{i}", spec=TaskSpec(),
                    status=TaskStatus(state=state))

    await store.update(lambda tx: [
        tx.create(mk_task(i, TaskState.RUNNING)) for i in range(5)])
    await store.update(lambda tx: tx.create(Node(
        id="n1", spec=NodeSpec(annotations=Annotations(name="n1")),
        status=NodeStatus(state=NodeState.READY))))
    await pump()
    assert coll.snapshot()["swarm_task_running"] == 5

    # update: state transition moves between gauges
    def move(tx):
        t = tx.get("task", "t0").copy()
        t.status.state = TaskState.FAILED
        tx.update(t)
    await store.update(move)
    # remove
    await store.update(lambda tx: tx.delete("task", "t1"))
    await pump()
    snap = coll.snapshot()
    assert snap["swarm_task_running"] == 3
    assert snap["swarm_task_failed"] == 1

    # the incremental gauges equal a from-scratch recount
    fresh = Collector(store)
    fresh._recount()
    for k, v in fresh.gauges.items():
        if k != "swarm_manager_leader":
            assert snap.get(k, 0) == v, k

    # bulk restore publishes no object events: the next event resyncs.
    # The post-restore commit creates SEVERAL objects in one transaction —
    # the store applies every mutation before publishing the events, so
    # the resync's recount already includes all of them and the buffered
    # events must be discarded, not applied on top (double-count bug).
    saved = store.save()
    store.restore(saved)
    await store.update(lambda tx: [
        tx.create(mk_task(99, TaskState.NEW)),
        tx.create(mk_task(98, TaskState.NEW)),
        tx.create(mk_task(97, TaskState.NEW))])
    await pump()
    snap2 = coll.snapshot()
    assert snap2["swarm_task_running"] == 3   # resynced, not drifted
    assert snap2["swarm_task_new"] == 3       # counted once, not twice
    # subsequent incremental accounting still exact
    await store.update(lambda tx: tx.delete("task", "t99"))
    await pump()
    assert coll.snapshot()["swarm_task_new"] == 2
    await coll.stop()


@async_test
async def test_metrics_collector_restore_on_quiet_store():
    """A bulk restore publishes no per-object events, so on a QUIET
    cluster nothing ever wakes the event loop to notice the generation
    bump — snapshot() must resync at scrape time, or a freshly promoted
    follower serves pre-restore counts until some unrelated commit."""
    store = MemoryStore()
    coll = Collector(store)
    await coll.start()

    def mk_task(i, state):
        return Task(id=f"t{i}", spec=TaskSpec(),
                    status=TaskStatus(state=state))

    await store.update(lambda tx: [
        tx.create(mk_task(i, TaskState.RUNNING)) for i in range(3)])
    await pump()
    assert coll.snapshot()["swarm_task_running"] == 3

    saved = store.save()
    await store.update(lambda tx: tx.delete("task", "t0"))
    await pump()
    assert coll.snapshot()["swarm_task_running"] == 2

    # roll back to the snapshot; NO commit follows, so no event arrives
    store.restore(saved)
    assert coll.snapshot()["swarm_task_running"] == 3
    # incremental accounting still exact after the scrape-time resync
    await store.update(lambda tx: tx.delete("task", "t1"))
    await pump()
    assert coll.snapshot()["swarm_task_running"] == 2
    await coll.stop()


@async_test
async def test_resourceapi_attach_detach():
    store = MemoryStore()
    api = ResourceApi(store)
    await store.update(lambda tx: [
        tx.create(Network(id="net1", spec=NetworkSpec(
            annotations=Annotations(name="overlay")))),
        tx.create(Node(id="n1", spec=NodeSpec(
            annotations=Annotations(name="n1")))),
    ])
    with pytest.raises(ResourceError):
        await api.attach_network("n1", "missing")
    tid = await api.attach_network("n1", "net1", container_id="abc")
    t = store.get("task", tid)
    assert t.node_id == "n1" and t.spec.networks == ["net1"]
    await api.detach_network(tid)
    assert store.get("task", tid) is None

"""In-process cluster harness for integration tests.

Reference: integration/cluster.go (testCluster :28 — AddManager, AddAgent,
RemoveNode, SetNodeRole, Leader, CreateService …) and integration/node.go
(testNode with Pause for restart-preserving-state tests).  Full
``swarmkit_tpu.node.Node`` objects (manager+agent in one "process") share an
in-process raft Network and a dialer directory; workloads run on
TestExecutor fakes; everything runs on the real event loop with a fast
raft tick.
"""

from __future__ import annotations

import asyncio
import os
import tempfile
from typing import Optional

from swarmkit_tpu.agent.testutils import TestExecutor
from swarmkit_tpu.api import (
    Annotations, ContainerSpec, MembershipState, NodeRole, NodeSpec,
    ReplicatedService, ServiceSpec, TaskSpec, TaskState,
)
from swarmkit_tpu.api.objects import Node as ApiNode, NodeStatus
from swarmkit_tpu.manager.manager import Manager
from swarmkit_tpu.node import Node, NodeConfig
from swarmkit_tpu.raft.transport import Network

TICK = 0.05


class TestCluster:
    """reference: testCluster integration/cluster.go:28."""

    __test__ = False

    def __init__(self, seed: int = 3, network=None,
                 transport_factory=None) -> None:
        self.network = network if network is not None else Network(seed=seed)
        self.transport_factory = transport_factory
        self.tmp = tempfile.TemporaryDirectory(prefix="swarmkit-int-")
        self.nodes: dict[str, Node] = {}
        self.executors: dict[str, TestExecutor] = {}
        self._n = 0
        self.seed = seed

    # ------------------------------------------------------------------
    def _dialer(self, addr: str) -> Optional[Manager]:
        for node in self.nodes.values():
            m = node._running_manager()
            if m is not None and m.addr == addr:
                return m
        return None

    def leader(self) -> Optional[Manager]:
        for node in self.nodes.values():
            m = node._running_manager()
            if m is not None and m.is_leader() and m._is_leader:
                return m
        return None

    async def wait_leader(self, timeout: float = 20.0) -> Manager:
        return await self.poll(self.leader, "leader elected", timeout)

    async def poll(self, fn, what: str, timeout: float = 20.0):
        deadline = asyncio.get_running_loop().time() + timeout
        while True:
            val = fn()
            if val:
                return val
            if asyncio.get_running_loop().time() > deadline:
                raise AssertionError(f"timeout waiting for {what}")
            await asyncio.sleep(0.02)

    # ------------------------------------------------------------------
    def _config(self, node_id: str, is_manager: bool, join_addr: str,
                force_new_cluster: bool = False,
                executor=None) -> NodeConfig:
        self._n += 1
        ex = executor or TestExecutor(hostname=node_id)
        self.executors[node_id] = ex
        return NodeConfig(
            node_id=node_id,
            state_dir=os.path.join(self.tmp.name, node_id),
            executor=ex,
            network=self.network,
            dialer=self._dialer,
            listen_addr=f"{node_id}:4242",
            join_addr=join_addr,
            is_manager=is_manager,
            force_new_cluster=force_new_cluster,
            tick_interval=TICK,
            election_tick=4,
            heartbeat_tick=1,
            seed=self.seed + self._n,
            transport_factory=self.transport_factory)

    async def add_manager(self, node_id: str = "", executor=None) -> Node:
        """reference: AddManager cluster.go."""
        node_id = node_id or f"manager-{self._n + 1}"
        lead = self.leader()
        join = lead.addr if lead is not None else ""
        node = Node(self._config(node_id, is_manager=True, join_addr=join,
                                 executor=executor))
        self.nodes[node_id] = node
        await node.start()
        await self.wait_leader()
        # wait for the manager-role node record to exist — callers that
        # immediately demote another manager must see the true manager
        # count, or controlapi's last-manager safeguard misfires
        await self.poll(
            lambda: (l := self.leader()) is not None
            and (rec := l.store.get("node", node_id)) is not None
            and rec.role == NodeRole.MANAGER or None,
            f"{node_id} manager record", timeout=20)
        return node

    async def add_agent(self, node_id: str = "", executor=None) -> Node:
        """reference: AddAgent cluster.go — the CA join creates the node
        record; until the CA layer lands the harness seeds it."""
        node_id = node_id or f"agent-{self._n + 1}"
        lead = await self.wait_leader()
        await lead.store.update(lambda tx: tx.create(ApiNode(
            id=node_id,
            spec=NodeSpec(annotations=Annotations(name=node_id),
                          membership=MembershipState.ACCEPTED),
            status=NodeStatus())))
        node = Node(self._config(node_id, is_manager=False,
                                 join_addr=lead.addr, executor=executor))
        self.nodes[node_id] = node
        await node.start()
        return node

    async def remove_node(self, node_id: str, force: bool = False) -> None:
        node = self.nodes.pop(node_id)
        await node.stop()
        self.network.unregister(node.addr)
        lead = self.leader()
        if lead is not None:
            try:
                await lead.control_api.remove_node(node_id, force=force)
            except Exception:
                pass

    async def set_node_role(self, node_id: str, role: NodeRole) -> None:
        """reference: SetNodeRole cluster.go — via control api.  Retries
        out-of-sequence failures like any real control client: concurrent
        status writes bump the node version between read and update."""
        deadline = asyncio.get_running_loop().time() + 20
        while True:
            lead = await self.wait_leader()
            cur = lead.control_api.get_node(node_id)
            spec = cur.spec.copy()
            spec.desired_role = role
            try:
                await lead.control_api.update_node(
                    node_id, spec, version=cur.meta.version.index)
                return
            except Exception:
                if asyncio.get_running_loop().time() > deadline:
                    raise
                await asyncio.sleep(0.02)

    async def stop_node(self, node_id: str) -> Node:
        """Stop without removing state (reference: testNode.Pause)."""
        node = self.nodes[node_id]
        await node.stop()
        self.network.unregister(node.addr)
        return node

    async def restart_node(self, node_id: str,
                           force_new_cluster: bool = False) -> Node:
        old = self.nodes[node_id]
        cfg = old.config
        cfg.force_new_cluster = force_new_cluster
        cfg.join_addr = ""
        node = Node(cfg)
        self.nodes[node_id] = node
        await node.start()
        return node

    async def stop_all(self) -> None:
        for node in list(self.nodes.values()):
            try:
                await node.stop()
            except Exception:
                pass
        close = getattr(self.network, "close", None)
        if close is not None:   # DeviceMeshNet owns a pump task
            close()

    # ------------------------------------------------------------------
    async def create_service(self, name: str = "web", replicas: int = 2,
                             image: str = "img"):
        lead = await self.wait_leader()
        return await lead.control_api.create_service(ServiceSpec(
            annotations=Annotations(name=name),
            task=TaskSpec(container=ContainerSpec(image=image)),
            replicated=ReplicatedService(replicas=replicas)))

    def running_tasks(self, service_id: str) -> list:
        lead = self.leader()
        if lead is None:
            return []
        from swarmkit_tpu.store.by import ByService

        return [t for t in lead.store.find("task", ByService(service_id))
                if t.status.state == TaskState.RUNNING
                and t.desired_state <= TaskState.RUNNING]

    async def poll_cluster_ready(self, managers: int, workers: int,
                                 timeout: float = 30.0) -> None:
        """reference: pollClusterReady integration_test.go:71."""
        def ready():
            lead = self.leader()
            if lead is None:
                return False
            nodes = lead.store.find("node")
            from swarmkit_tpu.api import NodeState

            ready_nodes = [n for n in nodes
                           if n.status.state == NodeState.READY]
            mgrs = [n for n in ready_nodes if n.role == NodeRole.MANAGER]
            wrks = [n for n in ready_nodes if n.role == NodeRole.WORKER]
            return len(mgrs) == managers and len(wrks) == workers
        await self.poll(ready, f"{managers} managers + {workers} workers "
                        "ready", timeout)

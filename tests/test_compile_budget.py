"""Compile-time memory budget for the headline bench shape.

The tiled log axis exists so tick cost — and XLA's temp allocation —
scales with the active window, not log capacity.  This pins that property
at compile time: lowering the headline `run_ticks` program (n=4096,
L=8192, tiled, static members) must stay under a temp-memory budget that
the full-pass kernel CANNOT meet (it materializes whole [N, L] buffer
copies per tick: ~709 MB temp vs ~378 MB tiled when this was pinned).  A
regression that re-introduces full-width materialization — a fusion
regression, a new cross-buffer coupling, a dropped in-place DUS chain —
trips this without running a single tick.

CPU-backend numbers; the budget is about the program structure XLA emits,
which the differential and DST suites pin for value-identity.

Lever discipline: the tick kernel now has three independent lowering
levers — log_chunk (tiled log axis), peer_chunk (banded quorum
reductions), active_rows (role-sparse progress slabs) — and each budget
was measured with ALL THREE at known settings.  Every pin passes all
three explicitly and its comment names which one is under test, so a
future lever (or a changed default) cannot silently move a pin's
premise: a pin that fails after a default change is telling you to
re-measure, not to relax the budget.
"""

import re

import pytest

from swarmkit_tpu.raft.sim import SimConfig, init_state
from swarmkit_tpu.raft.sim.run import run_ticks

# Between the measured tiled high-water mark (~464 MB: sparse progress
# active_rows=16 adds the cond's slab branch and a couple of defensive
# [N, N] copies over the dense-progress ~344 MB) and the full-pass log
# kernel's (~709 MB): headroom for compiler drift, hard fail on any
# full-width [N, L] materialization creeping back in.
TEMP_BUDGET_BYTES = 512 * 1024 * 1024


def test_headline_tiled_compile_fits_temp_budget():
    # Lever under test: log_chunk (tiled).  Held fixed: peer_chunk=1024
    # (banded), active_rows=16 (sparse progress) — the headline defaults.
    cfg = SimConfig(n=4096, log_len=8192, window=2048, apply_batch=2048,
                    max_props=2048, keep=500, static_members=True,
                    log_chunk=1024, peer_chunk=1024, active_rows=16)
    assert cfg.tiled and cfg.peer_tiled and cfg.active_rows_on
    st = init_state(cfg)
    compiled = run_ticks.lower(st, cfg, 8, prop_count=64).compile()
    stats = compiled.memory_analysis()
    assert stats is not None, "backend exposes no memory analysis"
    temp = stats.temp_size_in_bytes
    assert temp > 0, "suspicious zero temp size — analysis not populated"
    assert temp <= TEMP_BUDGET_BYTES, (
        f"tiled headline compile uses {temp / 2**20:.0f} MiB temp, over "
        f"the {TEMP_BUDGET_BYTES / 2**20:.0f} MiB budget — a full-width "
        f"[N, L] materialization likely crept back into the tick kernel")


def test_small_tiled_compile_fits_scaled_budget():
    """Tier-1-sized version of the same pin (n=256): catches the same
    full-materialization regressions in seconds.  Budget scaling: tiled
    temp is dominated by per-row O(window)/O(band) scratch, so 1/16 the
    rows gets 1/16 the budget (plus a small constant floor).

    Lever under test: log_chunk.  Held fixed: peer_chunk=0 (n=256 is
    below the band size, so banding is off either way), active_rows=16
    (sparse progress; measured 21.2 MiB vs 20.6 dense — well inside the
    scaled budget)."""
    cfg = SimConfig(n=256, log_len=8192, window=2048, apply_batch=2048,
                    max_props=2048, keep=500, static_members=True,
                    log_chunk=1024, peer_chunk=0, active_rows=16)
    st = init_state(cfg)
    compiled = run_ticks.lower(st, cfg, 8, prop_count=64).compile()
    stats = compiled.memory_analysis()
    assert stats is not None, "backend exposes no memory analysis"
    temp = stats.temp_size_in_bytes
    assert 0 < temp <= TEMP_BUDGET_BYTES // 16 + 8 * 2**20, (
        f"tiled n=256 compile uses {temp / 2**20:.0f} MiB temp — a "
        f"full-width [N, L] materialization likely crept back in")


# ---- peer-axis pins ---------------------------------------------------------
# The banded hierarchical quorum reductions (cfg.peer_chunk) exist so the
# tick's tally/bisect phases never materialize full [N, N] intermediates.
# On the dynamic-membership path the dense kernel MUST write at least the
# [N, N] i32 match_eff buffer (where(member, match, -1): 64 MiB at
# n=4096) before bisecting; the banded kernel folds the member band into
# each [N, peer_chunk] pass instead.  Measured when pinned: banded
# 195 MiB vs dense 258 MiB temp — the budget sits between, so the banded
# lowering passes a budget the dense lowering cannot meet, and a fusion
# regression that re-materializes an [N, N] intermediate in the banded
# path trips this without running a tick.
#
# Lever under test: peer_chunk.  Held fixed: log_chunk=128 (tiled),
# active_rows=16 (sparse progress; the quoted budgets were re-measured
# with the slab lowering on — it adds ~15 MiB to both variants).

PEER_SHAPE = dict(n=4096, log_len=1024, window=128, apply_batch=128,
                  max_props=128, keep=100, static_members=False,
                  log_chunk=128, active_rows=16)
PEER_TEMP_BUDGET = 224 * 1024 * 1024


def _temp_bytes(cfg, ticks=8, prop_count=64, state=None):
    st = init_state(cfg) if state is None else state
    compiled = run_ticks.lower(st, cfg, ticks,
                               prop_count=prop_count).compile()
    stats = compiled.memory_analysis()
    assert stats is not None, "backend exposes no memory analysis"
    temp = stats.temp_size_in_bytes
    assert temp > 0, "suspicious zero temp size — analysis not populated"
    return temp


def test_peer_tiled_compile_fits_budget_dense_cannot():
    banded = _temp_bytes(SimConfig(**PEER_SHAPE, peer_chunk=1024))
    dense = _temp_bytes(SimConfig(**PEER_SHAPE, peer_chunk=0))
    assert banded <= PEER_TEMP_BUDGET, (
        f"banded peer compile uses {banded / 2**20:.0f} MiB temp, over "
        f"the {PEER_TEMP_BUDGET / 2**20:.0f} MiB budget — an [N, N] "
        f"intermediate likely crept back into a quorum reduction")
    assert dense > PEER_TEMP_BUDGET, (
        f"dense peer compile uses only {dense / 2**20:.0f} MiB temp — the "
        f"pin's premise (dense cannot meet the banded budget) no longer "
        f"holds; re-measure and move PEER_TEMP_BUDGET")


# ---- role-sparse progress pins ----------------------------------------------
# The [A, N] progress slabs (cfg.active_rows) exist so the steady-state
# tick's elementwise per-peer writes — match/next/granted/rejection
# bookkeeping and the ack folds feeding them — run at [A, N] instead of
# [N, N].  Temp size cannot pin this one: the sparse program carries the
# bit-identical dense fallback as the other lax.cond branch, so its
# peak temp is a strict superset of the dense program's.  What IS
# compile-visible is the slab working set itself: the optimized HLO of
# the sparse lowering contains hundreds of [A, N]-shaped ops (gathers,
# slab elementwise updates, scatter sources), and the dense elementwise
# lowering contains exactly zero.  A is chosen so [A, N] collides with
# no other shape in the program.

SPARSE_SHAPE = dict(n=256, log_len=1024, window=128, apply_batch=128,
                    max_props=128, keep=100, static_members=True,
                    log_chunk=128, peer_chunk=64)


def _slab_op_count(cfg, a, ticks=4, prop_count=8):
    st = init_state(cfg)
    txt = run_ticks.lower(st, cfg, ticks,
                          prop_count=prop_count).compile().as_text()
    return len(re.findall(rf"\[{a},{cfg.n}\]", txt))


def test_sparse_progress_lowers_slab_writes_dense_does_not():
    # Lever under test: active_rows.  Held fixed: log_chunk=128 (tiled),
    # peer_chunk=64 (banded).  Measured when pinned: 998 [24, 256] ops
    # in the sparse program, 0 in the dense one — the floor of 100 is
    # compiler-drift headroom, not a tight bound.
    sparse = _slab_op_count(SimConfig(**SPARSE_SHAPE, active_rows=24), 24)
    dense = _slab_op_count(SimConfig(**SPARSE_SHAPE, active_rows=0), 24)
    assert sparse >= 100, (
        f"sparse progress compile has only {sparse} [24, 256]-shaped ops "
        f"— the active_rows lowering is no longer running the per-peer "
        f"progress updates on [A, N] slabs")
    assert dense == 0, (
        f"dense progress compile has {dense} [24, 256]-shaped ops — the "
        f"pin's premise (the dense elementwise lowering emits no "
        f"slab-shaped work) no longer holds; re-measure")


@pytest.mark.slow
def test_sharded_32k_compile_has_no_full_peer_buffer():
    """The n=32768 headline rung: row-sharded over the 8-virtual-device
    mesh with banded peer reductions, the lowered program must never
    materialize an UNSHARDED (replicated) [N, N] temp.  Per-device temps
    at this shape are row slabs — [N/8, N] i32 is 512 MiB, and the scan
    double-buffers a few of them: 2304 MiB measured when pinned (1920
    MiB re-measured with active_rows=16 — the [A, N] slabs retire some
    full row-slab temps even inside the cond).  The
    budget adds ~20% compiler-drift headroom yet stays below the
    smallest possible full-[N, N] addition (a replicated bool is 1 GiB,
    an i32 4 GiB), so any quorum reduction falling back to a gathered
    full-width intermediate trips it.  Compile-only: execution at this
    scale is the accelerator headline; the CPU bench runs the reduced
    4096-row rung of the same config (bench.py 32768-sharded)."""
    from swarmkit_tpu.parallel import row_mesh, shard_rows

    # Lever under test: peer_chunk under sharding.  Held fixed:
    # log_chunk=0 (L=256 is already small), active_rows=16 (sparse).
    cfg = SimConfig(n=32768, log_len=256, window=32, apply_batch=32,
                    max_props=32, keep=16, static_members=True,
                    log_chunk=0, peer_chunk=1024, active_rows=16)
    assert cfg.peer_tiled and cfg.num_peer_chunks == 32
    mesh = row_mesh(cfg.n)
    assert len(mesh.devices.ravel()) == 8, "8-device CPU mesh missing"
    st = shard_rows(init_state(cfg), mesh)
    temp = _temp_bytes(cfg, ticks=4, prop_count=8, state=st)
    assert temp <= 2816 * 1024 * 1024, (
        f"sharded n=32768 compile uses {temp / 2**20:.0f} MiB temp "
        f"(2304 MiB of row-slab scratch when pinned) — a replicated "
        f"full [N, N] buffer (>= 1 GiB) was likely materialized in the "
        f"banded tick")

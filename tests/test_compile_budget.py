"""Compile-time memory budget for the headline bench shape.

The tiled log axis exists so tick cost — and XLA's temp allocation —
scales with the active window, not log capacity.  This pins that property
at compile time: lowering the headline `run_ticks` program (n=4096,
L=8192, tiled, static members) must stay under a temp-memory budget that
the full-pass kernel CANNOT meet (it materializes whole [N, L] buffer
copies per tick: ~709 MB temp vs ~378 MB tiled when this was pinned).  A
regression that re-introduces full-width materialization — a fusion
regression, a new cross-buffer coupling, a dropped in-place DUS chain —
trips this without running a single tick.

CPU-backend numbers; the budget is about the program structure XLA emits,
which the differential and DST suites pin for value-identity.
"""

from swarmkit_tpu.raft.sim import SimConfig, init_state
from swarmkit_tpu.raft.sim.run import run_ticks

# Between the measured tiled high-water mark (~378 MB) and the full-pass
# kernel's (~709 MB): headroom for compiler drift, hard fail on any
# full-width materialization creeping back in.
TEMP_BUDGET_BYTES = 512 * 1024 * 1024


def test_headline_tiled_compile_fits_temp_budget():
    cfg = SimConfig(n=4096, log_len=8192, window=2048, apply_batch=2048,
                    max_props=2048, keep=500, static_members=True,
                    log_chunk=1024)
    assert cfg.tiled
    st = init_state(cfg)
    compiled = run_ticks.lower(st, cfg, 8, prop_count=64).compile()
    stats = compiled.memory_analysis()
    assert stats is not None, "backend exposes no memory analysis"
    temp = stats.temp_size_in_bytes
    assert temp > 0, "suspicious zero temp size — analysis not populated"
    assert temp <= TEMP_BUDGET_BYTES, (
        f"tiled headline compile uses {temp / 2**20:.0f} MiB temp, over "
        f"the {TEMP_BUDGET_BYTES / 2**20:.0f} MiB budget — a full-width "
        f"[N, L] materialization likely crept back into the tick kernel")


def test_small_tiled_compile_fits_scaled_budget():
    """Tier-1-sized version of the same pin (n=256): catches the same
    full-materialization regressions in seconds.  Budget scaling: tiled
    temp is dominated by per-row O(window)/O(band) scratch, so 1/16 the
    rows gets 1/16 the budget (plus a small constant floor)."""
    cfg = SimConfig(n=256, log_len=8192, window=2048, apply_batch=2048,
                    max_props=2048, keep=500, static_members=True,
                    log_chunk=1024)
    st = init_state(cfg)
    compiled = run_ticks.lower(st, cfg, 8, prop_count=64).compile()
    stats = compiled.memory_analysis()
    assert stats is not None, "backend exposes no memory analysis"
    temp = stats.temp_size_in_bytes
    assert 0 < temp <= TEMP_BUDGET_BYTES // 16 + 8 * 2**20, (
        f"tiled n=256 compile uses {temp / 2**20:.0f} MiB temp — a "
        f"full-width [N, L] materialization likely crept back in")

"""In-process integration suite: full Nodes (manager+agent) over a shared
raft network.

Reference scenarios: integration/integration_test.go (:183-908) — cluster
create, service create, node ops, demote/promote matrices incl. demoting
the leader, restart leader, force-new-cluster, node rejoin.
"""

import asyncio

import pytest

from swarmkit_tpu.api import NodeRole, NodeState, TaskState
from swarmkit_tpu.store.by import ByService
from tests.conftest import async_test, requires_cryptography
from tests.integration_harness import TestCluster


@async_test
async def test_cluster_and_service_create():
    """reference: TestClusterCreate + TestServiceCreate."""
    c = TestCluster()
    try:
        await c.add_manager()
        await c.add_agent()
        await c.add_agent()
        await c.poll_cluster_ready(managers=1, workers=2)

        svc = await c.create_service(replicas=4)
        await c.poll(lambda: len(c.running_tasks(svc.id)) == 4,
                     "4 replicas running")
        used = {t.node_id for t in c.running_tasks(svc.id)}
        assert len(used) >= 2  # spread over the workers (manager also runs)
    finally:
        await c.stop_all()


@async_test
async def test_multi_manager_replication_and_leader_restart():
    """reference: TestRestartLeader integration_test.go."""
    c = TestCluster()
    try:
        await c.add_manager("m1")
        await c.add_manager("m2")
        await c.add_manager("m3")
        lead = await c.wait_leader()
        assert lead.node_id == "m1"
        svc = await c.create_service(replicas=2)
        await c.poll(lambda: len(c.running_tasks(svc.id)) == 2,
                     "2 replicas running")

        await c.stop_node(lead.node_id)
        new_lead = await c.poll(
            lambda: (l := c.leader()) is not None
            and l.node_id != "m1" and l or None,
            "failover leader", timeout=30)
        # cluster still serves reads and writes
        assert new_lead.store.get("service", svc.id) is not None
        svc2 = await c.create_service(name="after-failover")
        assert new_lead.store.get("service", svc2.id) is not None

        # the old leader comes back as a follower and catches up
        await c.restart_node("m1")
        m1 = c.nodes["m1"]
        await c.poll(
            lambda: m1._running_manager() is not None
            and m1._running_manager().store.get("service", svc2.id)
            is not None,
            "restarted leader caught up", timeout=30)
    finally:
        await c.stop_all()


@async_test
async def test_promote_agent_to_manager_and_demote():
    """reference: TestDemotePromote / TestPromoteDemote."""
    c = TestCluster()
    try:
        await c.add_manager("m1")
        await c.add_agent("a1")
        await c.poll_cluster_ready(managers=1, workers=1)

        await c.set_node_role("a1", NodeRole.MANAGER)
        # role manager flips role; node supervisor starts a manager
        a1 = c.nodes["a1"]
        await c.poll(lambda: a1.is_manager() or None,
                     "a1 running a manager", timeout=30)
        lead = c.leader()
        await c.poll(lambda: len(lead.raft.cluster.members) == 2,
                     "raft membership grew to 2", timeout=30)

        # demote: raft member removed, manager stops
        await c.set_node_role("a1", NodeRole.WORKER)
        await c.poll(lambda: not a1.is_manager() or None,
                     "a1 manager stopped", timeout=40)
        await c.poll(lambda: len(c.leader().raft.cluster.members) == 1,
                     "raft membership back to 1", timeout=30)
        # still a functional worker
        svc = await c.create_service(replicas=2)
        await c.poll(lambda: len(c.running_tasks(svc.id)) == 2,
                     "tasks running after demote")
    finally:
        await c.stop_all()


@async_test
async def test_demote_leader():
    """reference: TestDemoteLeader — demoting the leader transfers
    leadership and removes it from the member list."""
    c = TestCluster()
    try:
        await c.add_manager("m1")
        await c.add_manager("m2")
        await c.add_manager("m3")
        lead = await c.wait_leader()
        assert lead.node_id == "m1"

        await c.set_node_role("m1", NodeRole.WORKER)
        new_lead = await c.poll(
            lambda: (l := c.leader()) is not None and l.node_id != "m1"
            and l or None,
            "leadership moved off m1", timeout=40)
        await c.poll(
            lambda: len(new_lead.raft.cluster.members) == 2,
            "m1 removed from raft members", timeout=40)
        m1 = c.nodes["m1"]
        await c.poll(lambda: not m1.is_manager() or None,
                     "m1's manager stopped", timeout=40)
    finally:
        await c.stop_all()


@async_test
async def test_force_new_cluster_after_quorum_loss():
    """reference: TestForceNewCluster integration_test.go."""
    c = TestCluster()
    try:
        await c.add_manager("m1")
        await c.add_manager("m2")
        await c.add_manager("m3")
        svc = await c.create_service(replicas=1)
        lead = await c.wait_leader()
        assert lead.node_id == "m1"

        # lose quorum: kill two of three managers
        await c.stop_node("m2")
        await c.stop_node("m3")
        await asyncio.sleep(1.0)

        # recover the survivor as a single-member cluster
        await c.stop_node("m1")
        await c.restart_node("m1", force_new_cluster=True)
        m1 = c.nodes["m1"]
        new_lead = await c.poll(c.leader, "single-member leader", timeout=30)
        assert new_lead.node_id == "m1"
        assert len(new_lead.raft.cluster.members) == 1
        # state survived
        assert new_lead.store.get("service", svc.id) is not None
        # and the cluster takes writes again
        svc2 = await c.create_service(name="recovered")
        assert new_lead.store.get("service", svc2.id) is not None
    finally:
        await c.stop_all()


@async_test
async def test_worker_restart_rejoins_and_resumes():
    """reference: TestNodeRejoins — an agent restart re-registers and its
    tasks survive."""
    c = TestCluster()
    try:
        await c.add_manager("m1")
        await c.add_agent("a1")
        await c.poll_cluster_ready(managers=1, workers=1)
        svc = await c.create_service(replicas=2)
        await c.poll(lambda: len(c.running_tasks(svc.id)) == 2,
                     "2 running before restart")

        await c.stop_node("a1")
        await c.restart_node("a1")
        lead = c.leader()
        await c.poll(
            lambda: lead.store.get("node", "a1").status.state
            == NodeState.READY or None,
            "a1 re-registered", timeout=30)
        await c.poll(lambda: len(c.running_tasks(svc.id)) == 2,
                     "2 running after restart", timeout=30)
    finally:
        await c.stop_all()


@async_test
@requires_cryptography
async def test_join_with_token_full_ca_flow():
    """reference: TestNodeJoinWithSecret / wrong-cert join rejection — a
    worker joins with the real join token (no harness-seeded node record);
    a bad token is rejected."""
    from swarmkit_tpu.node import Node, NodeConfig
    from swarmkit_tpu.agent.testutils import TestExecutor
    import os

    c = TestCluster()
    try:
        await c.add_manager("m1")
        lead = await c.wait_leader()
        cluster_obj = lead.store.find("cluster")[0]
        token = cluster_obj.root_ca.join_token_worker
        assert token.startswith("SWMTKN-1-")

        cfg = NodeConfig(
            node_id="joiner",  # replaced by the CA-assigned id
            state_dir=os.path.join(c.tmp.name, "joiner"),
            executor=TestExecutor(hostname="joiner"),
            network=c.network, dialer=c._dialer,
            listen_addr="joiner:4242", join_addr=lead.addr,
            join_token=token, tick_interval=0.05, election_tick=4, seed=99)
        node = Node(cfg)
        c.nodes["joiner"] = node
        await node.start()
        # CA honored the vacant requested id and issued a worker identity
        assert node.node_id == "joiner"
        assert node.security is not None and not node.security.is_manager
        assert node.security.org == lead.store.find("cluster")[0].id

        from swarmkit_tpu.api import NodeState
        await c.poll(
            lambda: (n := lead.store.get("node", node.node_id)) is not None
            and n.status.state == NodeState.READY or None,
            "token-joined worker READY", timeout=30)

        # tasks land on it
        svc = await c.create_service(replicas=3)
        await c.poll(lambda: len(c.running_tasks(svc.id)) == 3,
                     "tasks running incl. token-joined node")

        # a forged token is rejected outright
        bad_cfg = NodeConfig(
            node_id="bad", state_dir=os.path.join(c.tmp.name, "bad"),
            executor=TestExecutor(hostname="bad"),
            network=c.network, dialer=c._dialer,
            listen_addr="bad:4242", join_addr=lead.addr,
            join_token="SWMTKN-1-deadbeef-cafe",
            tick_interval=0.05, election_tick=4, seed=100)
        bad = Node(bad_cfg)
        with pytest.raises(Exception):
            await bad.start()
        await bad.stop()
    finally:
        await c.stop_all()


@async_test
@requires_cryptography
async def test_manager_join_with_manager_token():
    """A second manager joins purely via the manager join token."""
    from swarmkit_tpu.node import Node, NodeConfig
    from swarmkit_tpu.agent.testutils import TestExecutor
    import os

    c = TestCluster()
    try:
        await c.add_manager("m1")
        lead = await c.wait_leader()
        token = lead.store.find("cluster")[0].root_ca.join_token_manager

        cfg = NodeConfig(
            node_id="m2-tmp", state_dir=os.path.join(c.tmp.name, "m2"),
            executor=TestExecutor(hostname="m2"),
            network=c.network, dialer=c._dialer,
            listen_addr="m2:4242", join_addr=lead.addr,
            join_token=token, is_manager=True,
            tick_interval=0.05, election_tick=4, seed=101)
        node = Node(cfg)
        c.nodes["m2"] = node
        await node.start()
        assert node.security is not None and node.security.is_manager
        await c.poll(lambda: len(lead.raft.cluster.members) == 2,
                     "raft grew to 2 via token join", timeout=30)
    finally:
        await c.stop_all()


@async_test
async def test_demote_downed_manager():
    """reference: integration_test.go demotion matrix — demoting a manager
    that is DOWN must still remove its raft member and flip its role, so
    the cluster doesn't wait on a dead peer."""
    c = TestCluster()
    try:
        await c.add_manager("m1")
        await c.add_manager("m2")
        await c.add_manager("m3")
        lead = await c.wait_leader()
        victim = "m3" if lead.node_id != "m3" else "m2"

        await c.stop_node(victim)
        await c.set_node_role(victim, NodeRole.WORKER)
        await c.poll(
            lambda: (l := c.leader()) is not None
            and len(l.raft.cluster.members) == 2 or None,
            "downed manager's raft member removed", timeout=40)
        await c.poll(
            lambda: (l := c.leader()) is not None
            and (n := l.store.get("node", victim)) is not None
            and n.role == NodeRole.WORKER or None,
            "downed manager's role flipped", timeout=40)
        # the survivors still commit
        svc = await c.create_service(replicas=2)
        await c.poll(lambda: len(c.running_tasks(svc.id)) == 2,
                     "tasks running after demoting a downed manager")
    finally:
        await c.stop_all()


# ---------------------------------------------------------------------------
# The whole orchestrator over the DEVICE-MESH transport: manager quorum
# consensus rides the [N, N] device mailbox wire while the service stack
# (controlapi -> orchestrator -> scheduler -> dispatcher -> executor) runs
# on top of it.  This is the reference acceptance gate one level above the
# raft suite (integration/integration_test.go:183-908 over the real gRPC
# transport; here over SURVEY §7's device backend).
# ---------------------------------------------------------------------------

def _device_cluster():
    from swarmkit_tpu.transport import DeviceMeshNet, DeviceMeshTransport
    return TestCluster(network=DeviceMeshNet(seed=5, rows=8),
                       transport_factory=DeviceMeshTransport)


@async_test
async def test_device_mesh_service_create_and_leader_kill():
    """3 managers + 2 agents with consensus on the device-mesh transport:
    CreateService -> orchestrate -> schedule -> dispatch -> executor
    RUNNING; kill the leader mid-flight; the service survives and scales."""
    c = _device_cluster()
    try:
        await c.add_manager("m1")
        await c.add_manager("m2")
        await c.add_manager("m3")
        await c.add_agent("a1")
        await c.add_agent("a2")
        await c.poll_cluster_ready(managers=3, workers=2)

        svc = await c.create_service(replicas=4)
        await c.poll(lambda: len(c.running_tasks(svc.id)) == 4,
                     "4 replicas running on device transport", timeout=60)

        # leader kill mid-flight: quorum survives on the wire, a new
        # leader takes over, and the service keeps reconciling
        lead = await c.wait_leader()
        await c.stop_node(lead.node_id)
        new_lead = await c.poll(
            lambda: (l := c.leader()) is not None
            and l.node_id != lead.node_id and l or None,
            "failover leader on device transport", timeout=60)
        assert new_lead.store.get("service", svc.id) is not None

        # post-failover writes commit through the device wire
        svc2 = await c.create_service(name="after-device-failover",
                                      replicas=2)
        await c.poll(lambda: len(c.running_tasks(svc2.id)) == 2,
                     "post-failover service running", timeout=60)
    finally:
        await c.stop_all()


@async_test
async def test_swarm_bench_device_transport_mode():
    """`swarm-bench --transport=device` measures time-to-N-RUNNING with the
    manager quorum on the device-mesh wire (reference harness role:
    cmd/swarm-bench/benchmark.go:38)."""
    from swarmkit_tpu.cmd.swarm_bench import bench

    r = await bench(replicas=8, workers=2, managers=3, transport="device")
    assert r["transport"] == "device"
    assert r["time_to_all_running_s"] > 0
    assert r["tasks_per_s"] > 0

"""In-process integration suite: full Nodes (manager+agent) over a shared
raft network.

Reference scenarios: integration/integration_test.go (:183-908) — cluster
create, service create, node ops, demote/promote matrices incl. demoting
the leader, restart leader, force-new-cluster, node rejoin.
"""

import asyncio

import pytest

from swarmkit_tpu.api import NodeRole, NodeState, TaskState
from swarmkit_tpu.store.by import ByService
from tests.conftest import async_test
from tests.integration_harness import TestCluster


@async_test
async def test_cluster_and_service_create():
    """reference: TestClusterCreate + TestServiceCreate."""
    c = TestCluster()
    try:
        await c.add_manager()
        await c.add_agent()
        await c.add_agent()
        await c.poll_cluster_ready(managers=1, workers=2)

        svc = await c.create_service(replicas=4)
        await c.poll(lambda: len(c.running_tasks(svc.id)) == 4,
                     "4 replicas running")
        used = {t.node_id for t in c.running_tasks(svc.id)}
        assert len(used) >= 2  # spread over the workers (manager also runs)
    finally:
        await c.stop_all()


@async_test
async def test_multi_manager_replication_and_leader_restart():
    """reference: TestRestartLeader integration_test.go."""
    c = TestCluster()
    try:
        await c.add_manager("m1")
        await c.add_manager("m2")
        await c.add_manager("m3")
        lead = await c.wait_leader()
        assert lead.node_id == "m1"
        svc = await c.create_service(replicas=2)
        await c.poll(lambda: len(c.running_tasks(svc.id)) == 2,
                     "2 replicas running")

        await c.stop_node(lead.node_id)
        new_lead = await c.poll(
            lambda: (l := c.leader()) is not None
            and l.node_id != "m1" and l or None,
            "failover leader", timeout=30)
        # cluster still serves reads and writes
        assert new_lead.store.get("service", svc.id) is not None
        svc2 = await c.create_service(name="after-failover")
        assert new_lead.store.get("service", svc2.id) is not None

        # the old leader comes back as a follower and catches up
        await c.restart_node("m1")
        m1 = c.nodes["m1"]
        await c.poll(
            lambda: m1._running_manager() is not None
            and m1._running_manager().store.get("service", svc2.id)
            is not None,
            "restarted leader caught up", timeout=30)
    finally:
        await c.stop_all()


@async_test
async def test_promote_agent_to_manager_and_demote():
    """reference: TestDemotePromote / TestPromoteDemote."""
    c = TestCluster()
    try:
        await c.add_manager("m1")
        await c.add_agent("a1")
        await c.poll_cluster_ready(managers=1, workers=1)

        await c.set_node_role("a1", NodeRole.MANAGER)
        # role manager flips role; node supervisor starts a manager
        a1 = c.nodes["a1"]
        await c.poll(lambda: a1.is_manager() or None,
                     "a1 running a manager", timeout=30)
        lead = c.leader()
        await c.poll(lambda: len(lead.raft.cluster.members) == 2,
                     "raft membership grew to 2", timeout=30)

        # demote: raft member removed, manager stops
        await c.set_node_role("a1", NodeRole.WORKER)
        await c.poll(lambda: not a1.is_manager() or None,
                     "a1 manager stopped", timeout=40)
        await c.poll(lambda: len(c.leader().raft.cluster.members) == 1,
                     "raft membership back to 1", timeout=30)
        # still a functional worker
        svc = await c.create_service(replicas=2)
        await c.poll(lambda: len(c.running_tasks(svc.id)) == 2,
                     "tasks running after demote")
    finally:
        await c.stop_all()


@async_test
async def test_demote_leader():
    """reference: TestDemoteLeader — demoting the leader transfers
    leadership and removes it from the member list."""
    c = TestCluster()
    try:
        await c.add_manager("m1")
        await c.add_manager("m2")
        await c.add_manager("m3")
        lead = await c.wait_leader()
        assert lead.node_id == "m1"

        await c.set_node_role("m1", NodeRole.WORKER)
        new_lead = await c.poll(
            lambda: (l := c.leader()) is not None and l.node_id != "m1"
            and l or None,
            "leadership moved off m1", timeout=40)
        await c.poll(
            lambda: len(new_lead.raft.cluster.members) == 2,
            "m1 removed from raft members", timeout=40)
        m1 = c.nodes["m1"]
        await c.poll(lambda: not m1.is_manager() or None,
                     "m1's manager stopped", timeout=40)
    finally:
        await c.stop_all()


@async_test
async def test_force_new_cluster_after_quorum_loss():
    """reference: TestForceNewCluster integration_test.go."""
    c = TestCluster()
    try:
        await c.add_manager("m1")
        await c.add_manager("m2")
        await c.add_manager("m3")
        svc = await c.create_service(replicas=1)
        lead = await c.wait_leader()
        assert lead.node_id == "m1"

        # lose quorum: kill two of three managers
        await c.stop_node("m2")
        await c.stop_node("m3")
        await asyncio.sleep(1.0)

        # recover the survivor as a single-member cluster
        await c.stop_node("m1")
        await c.restart_node("m1", force_new_cluster=True)
        m1 = c.nodes["m1"]
        new_lead = await c.poll(c.leader, "single-member leader", timeout=30)
        assert new_lead.node_id == "m1"
        assert len(new_lead.raft.cluster.members) == 1
        # state survived
        assert new_lead.store.get("service", svc.id) is not None
        # and the cluster takes writes again
        svc2 = await c.create_service(name="recovered")
        assert new_lead.store.get("service", svc2.id) is not None
    finally:
        await c.stop_all()


@async_test
async def test_worker_restart_rejoins_and_resumes():
    """reference: TestNodeRejoins — an agent restart re-registers and its
    tasks survive."""
    c = TestCluster()
    try:
        await c.add_manager("m1")
        await c.add_agent("a1")
        await c.poll_cluster_ready(managers=1, workers=1)
        svc = await c.create_service(replicas=2)
        await c.poll(lambda: len(c.running_tasks(svc.id)) == 2,
                     "2 running before restart")

        await c.stop_node("a1")
        await c.restart_node("a1")
        lead = c.leader()
        await c.poll(
            lambda: lead.store.get("node", "a1").status.state
            == NodeState.READY or None,
            "a1 re-registered", timeout=30)
        await c.poll(lambda: len(c.running_tasks(svc.id)) == 2,
                     "2 running after restart", timeout=30)
    finally:
        await c.stop_all()

"""Tracer tests: ring eviction, async parent propagation, and span-id
carriage across the gRPC wire (the cross-process reparenting seam).

The two-process test at the bottom is the wire contract's proof: process
A (a subprocess) opens a span and packs a dispatcher session request with
the real client packing code; process B (this one) unpacks it with the
real service-side logic and serves the session — the server-side
``dispatcher.session`` span must parent under A's span id, which only
ever crossed the boundary as bytes.
"""

import asyncio
import json
import os
import random
import subprocess
import sys

import msgpack

from swarmkit_tpu.metrics import trace
from tests.conftest import async_test

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


# ---------------------------------------------------------------------------
# ring eviction


def test_finished_ring_evicts_oldest_first():
    t = trace.Tracer(maxlen=4)
    for i in range(7):
        with t.span(f"s{i}"):
            pass
    names = [s.name for s in t.finished()]
    assert names == ["s3", "s4", "s5", "s6"]   # 0-2 evicted, order kept


def test_default_ring_bounded_at_max():
    t = trace.Tracer()
    for i in range(trace.MAX_FINISHED_SPANS + 25):
        t.finish(t.start(f"s{i}"))
    spans = t.finished()
    assert len(spans) == trace.MAX_FINISHED_SPANS
    assert spans[0].name == "s25"              # exactly the oldest 25 gone
    assert spans[-1].name == f"s{trace.MAX_FINISHED_SPANS + 24}"


def test_exception_recorded_and_context_restored():
    t = trace.Tracer()
    try:
        with t.span("boom"):
            raise RuntimeError("x")
    except RuntimeError:
        pass
    assert t.finished("boom")[0].attrs["error"] == "RuntimeError"
    assert trace.current_span_id() is None


# ---------------------------------------------------------------------------
# parent propagation


@async_test
async def test_parent_propagates_across_create_task():
    """contextvars snapshot at task creation: a span opened in the parent
    task is the parent of spans started inside asyncio.create_task."""
    t = trace.Tracer()
    done = asyncio.Event()

    async def child():
        with t.span("child.work"):
            pass
        done.set()

    with t.span("parent.request") as outer:
        task = asyncio.create_task(child())
        await done.wait()
        await task

    child_span = t.finished("child.work")[0]
    assert child_span.parent_id == outer.span_id
    # and the full ancestry walks back to the root
    chain = [s.name for s in trace.iter_ancestry(t.finished(), child_span)]
    assert chain == ["child.work", "parent.request"]


@async_test
async def test_sibling_tasks_do_not_inherit_each_other():
    t = trace.Tracer()

    async def one(name):
        with t.span(name):
            await asyncio.sleep(0)

    with t.span("root"):
        await asyncio.gather(one("a"), one("b"))
    a, b = t.finished("a")[0], t.finished("b")[0]
    root = t.finished("root")[0]
    assert a.parent_id == root.span_id == b.parent_id
    assert a.parent_id != a.span_id


def test_explicit_parent_id_beats_contextvar():
    t = trace.Tracer()
    with t.span("ambient"):
        s = t.start("wired", parent_id="deadbeef")
    assert s.parent_id == "deadbeef"


# ---------------------------------------------------------------------------
# span ids across the wire (two processes)

_CHILD_PROG = r"""
import json, sys
from swarmkit_tpu import rpc
from swarmkit_tpu.metrics import trace

with trace.DEFAULT.span("agent.session_loop", node="w1") as sp:
    req = rpc.pack_session_request("node1", None, "", "10.0.0.9:4242")
print(json.dumps({"span_id": sp.span_id, "req_hex": req.hex()}))
"""


@async_test
async def test_session_span_reparents_across_process_boundary():
    """Client packs in one OS process, server unpacks and serves in this
    one; the dispatcher.session span's parent must be the client's span
    id, carried only inside the request bytes."""
    env = dict(os.environ, PYTHONPATH=REPO, JAX_PLATFORMS="cpu")
    out = subprocess.run([sys.executable, "-c", _CHILD_PROG], env=env,
                         cwd=REPO, capture_output=True, text=True,
                         timeout=120)
    assert out.returncode == 0, out.stderr
    wire = json.loads(out.stdout.strip().splitlines()[-1])
    req = bytes.fromhex(wire["req_hex"])

    # service-side unpack (same tolerant shape as ClusterService.session)
    vals = msgpack.unpackb(req)
    node_id, desc_json, session_id, addr = vals[:4]
    parent_span = vals[4] if len(vals) > 4 else ""
    assert parent_span == wire["span_id"]

    # drive the real dispatcher with the carried parent
    from swarmkit_tpu.api import (
        Annotations, Node, NodeSpec, NodeState,
    )
    from swarmkit_tpu.api.objects import NodeStatus
    from swarmkit_tpu.manager.dispatcher import Dispatcher
    from swarmkit_tpu.store.memory import MemoryStore
    from swarmkit_tpu.utils.clock import FakeClock

    trace.DEFAULT.clear()
    clock = FakeClock()
    store = MemoryStore(clock=clock.now)
    await store.update(lambda tx: tx.create(Node(
        id=node_id, spec=NodeSpec(annotations=Annotations(name=node_id)),
        status=NodeStatus(state=NodeState.UNKNOWN))))
    d = Dispatcher(store, clock=clock, rng=random.Random(0))
    await d.start(mark_unknown=False)
    try:
        stream = d.session(node_id, None, session_id=session_id,
                           addr=addr, parent_span=parent_span)
        await stream.__anext__()           # first SessionMessage
        await stream.aclose()
    finally:
        await d.stop()

    server_span = trace.DEFAULT.finished("dispatcher.session")[-1]
    assert server_span.parent_id == wire["span_id"]
    # ids are process-local counters: both processes minted "...1"-ish
    # ids, so equality only holds because the value crossed as bytes
    assert server_span.span_id != server_span.parent_id


def test_old_four_element_session_request_still_accepted():
    """Pre-span clients pack 4 elements; the service-side slice keeps
    them working (rolling upgrade across manager versions)."""
    req = msgpack.packb(("n1", b"", "sess", "addr"))
    vals = msgpack.unpackb(req)
    node_id, desc_json, session_id, addr = vals[:4]
    parent_span = vals[4] if len(vals) > 4 else ""
    assert (node_id, session_id, addr) == ("n1", "sess", "addr")
    assert parent_span == ""


@async_test
async def test_control_call_payload_carries_span_id():
    """RemoteManager.control_call embeds the caller's span id in the JSON
    body (ClusterService.control reparents its dispatch span from it)."""
    from swarmkit_tpu.rpc import RemoteManager

    rm = RemoteManager("127.0.0.1:1")
    sent: list[bytes] = []

    async def fake_connect():
        pass

    async def fake_ctl(raw: bytes) -> bytes:
        sent.append(raw)
        return json.dumps({"result": {"ok": True}}).encode()

    rm._connect = fake_connect
    rm._ctl = fake_ctl
    with trace.DEFAULT.span("cli.update") as sp:
        result = await rm.control_call("update_node", {"id": "n1"})
    assert result == {"ok": True}
    req = json.loads(sent[0])
    assert req["span_id"] == sp.span_id
    assert req["method"] == "update_node"

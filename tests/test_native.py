"""Native WAL codec: native/python equivalence + property checks."""

import os
import random
import struct
import zlib

import pytest

from swarmkit_tpu.native import (
    STATUS_CORRUPT, STATUS_OK, STATUS_TORN_TAIL, PyWalCodec, _build_native,
)

native = _build_native()
codecs = [PyWalCodec()] + ([native] if native is not None else [])


def test_native_builds():
    """The toolchain is present in this image; the native codec must load."""
    assert native is not None, "g++ build of wal_codec.cpp failed"


@pytest.mark.parametrize("codec", codecs, ids=lambda c: c.name)
def test_frame_scan_round_trip(codec):
    rng = random.Random(5)
    bodies = [rng.randbytes(rng.randint(0, 2048)) for _ in range(200)]
    blob = codec.frame(bodies)
    out, status = codec.scan(blob)
    assert status == STATUS_OK
    assert out == bodies


@pytest.mark.parametrize("codec", codecs, ids=lambda c: c.name)
def test_torn_tail_dropped(codec):
    bodies = [b"alpha", b"beta", b"gamma"]
    blob = codec.frame(bodies)
    out, status = codec.scan(blob[:-3])   # truncate the last record
    assert status == STATUS_TORN_TAIL
    assert out == [b"alpha", b"beta"]
    # truncated mid-header too
    out, status = codec.scan(blob[: len(codec.frame([b"alpha"])) + 4])
    assert status == STATUS_TORN_TAIL
    assert out == [b"alpha"]


@pytest.mark.parametrize("codec", codecs, ids=lambda c: c.name)
def test_corrupt_midstream_detected(codec):
    bodies = [b"alpha", b"beta", b"gamma"]
    blob = bytearray(codec.frame(bodies))
    blob[9] ^= 0xFF   # flip a byte inside the first body
    out, status = codec.scan(bytes(blob))
    assert status == STATUS_CORRUPT
    assert out == []


def test_native_matches_python_bit_for_bit():
    if native is None:
        pytest.skip("no native codec")
    py = PyWalCodec()
    rng = random.Random(9)
    for _ in range(20):
        bodies = [rng.randbytes(rng.randint(0, 512))
                  for _ in range(rng.randint(0, 50))]
        assert native.frame(bodies) == py.frame(bodies)
    # crc parity with zlib
    blob = native.frame([b"x" * 1000])
    length, crc = struct.unpack_from("<II", blob, 0)
    assert crc == zlib.crc32(b"x" * 1000)


def test_wal_storage_uses_codec(tmp_path):
    """The raft WAL round-trips through the codec (whichever is active)."""
    from swarmkit_tpu.raft.messages import Entry, EntryType, HardState
    from swarmkit_tpu.raft.storage import EncryptedRaftLogger

    lg = EncryptedRaftLogger(str(tmp_path))
    lg.bootstrap_new()
    entries = [Entry(index=i, term=1, type=EntryType.NORMAL,
                     data=bytes([i]) * 64) for i in range(1, 51)]
    lg.save(HardState(term=1, vote=1, commit=50), entries)
    lg.close()

    lg2 = EncryptedRaftLogger(str(tmp_path))
    result = lg2.bootstrap_from_disk()
    assert [e.index for e in result.entries] == list(range(1, 51))
    assert result.hard_state.commit == 50
    lg2.close()

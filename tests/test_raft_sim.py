"""Invariant + behavior tests for the batched JAX raft kernel.

Safety properties asserted over full traces (the differential gate vs the
host golden core's semantics):
- Election safety: at most one leader per term, ever.
- Log matching / state-machine safety: nodes with equal `applied` have equal
  applied-stream checksums.
- Commit monotonicity, term monotonicity per node.
"""

import numpy as np
import pytest

import jax
import jax.numpy as jnp

from swarmkit_tpu.raft.sim import (
    LEADER, SimConfig, committed_entries, init_state, propose, run_ticks,
    run_until_leader, step, transfer_leadership,
)

SMALL = SimConfig(n=5, log_len=256, window=32, apply_batch=64, max_props=16,
                  keep=8, seed=1)

# jit once per (cfg, arg-presence) — eager per-op dispatch is too slow even
# at toy sizes.
step_j = jax.jit(step, static_argnames=("cfg",))
propose_j = jax.jit(propose, static_argnames=("cfg",))


def leaders_of(st):
    self_mem = np.asarray(st.member).diagonal()
    return np.flatnonzero(np.asarray(st.role == LEADER) & self_mem)


class TraceChecker:
    """Accumulates per-tick states and asserts raft safety invariants."""

    def __init__(self):
        self.term_leaders: dict[int, int] = {}
        self.prev_commit = None
        self.prev_term = None

    def observe(self, st):
        term = np.asarray(st.term)
        commit = np.asarray(st.commit)
        for lid in leaders_of(st):
            t = int(term[lid])
            seen = self.term_leaders.get(t)
            assert seen is None or seen == lid, \
                f"two leaders ({seen}, {lid}) in term {t}"
            self.term_leaders[t] = lid
        if self.prev_commit is not None:
            assert (commit >= self.prev_commit).all(), "commit went backwards"
            assert (term >= self.prev_term).all(), "term went backwards"
        self.prev_commit, self.prev_term = commit, term
        # state-machine safety: same applied => same checksum
        applied = np.asarray(st.applied)
        chk = np.asarray(st.apply_chk)
        by_applied: dict[int, int] = {}
        for a, c in zip(applied.tolist(), chk.tolist()):
            if a == 0:
                continue
            assert by_applied.setdefault(a, c) == c, \
                f"checksum divergence at applied={a}"


def drive(cfg, n_ticks, prop_count=0, drop_rate=0.0, crash=None, state=None):
    """Eager (non-scan) driver so invariants can be checked every tick."""
    st = state if state is not None else init_state(cfg)
    chk = TraceChecker()
    rng = np.random.default_rng(0)
    for t in range(n_ticks):
        if prop_count:
            payloads = jnp.arange(cfg.max_props, dtype=jnp.uint32) + t * 1000
            st = propose_j(st, cfg, payloads, jnp.asarray(prop_count))
        drop = None
        if drop_rate:
            drop = jnp.asarray(rng.random((cfg.n, cfg.n)) < drop_rate)
        alive = None
        if crash is not None:
            alive = jnp.asarray(crash(t, st))
        st = step_j(st, cfg, alive=alive, drop=drop)
        chk.observe(st)
    return st, chk


class TestElection:
    def test_elects_single_leader(self):
        st, chk = drive(SMALL, 40)
        assert len(leaders_of(st)) == 1
        # everyone agrees who leads
        lead = np.asarray(st.lead)
        assert len(set(lead.tolist())) == 1 and lead[0] >= 0

    def test_randomized_timeouts_differ(self):
        st = init_state(SMALL)
        to = np.asarray(st.timeout)
        assert len(set(to.tolist())) > 1
        assert (to >= SMALL.election_tick).all()
        assert (to < 2 * SMALL.election_tick).all()

    def test_run_until_leader(self):
        st, ticks = run_until_leader(init_state(SMALL), SMALL, max_ticks=200)
        assert int(ticks) < 200
        assert len(leaders_of(st)) == 1


class TestReplication:
    def test_steady_state_commit(self):
        st, _ = drive(SMALL, 30)
        st, chk = drive(SMALL, 20, prop_count=8, state=st)
        st, _ = drive(SMALL, 3, state=st)  # let commit index propagate
        commit = np.asarray(st.commit)
        # all nodes commit all proposals (8/tick * 20 ticks + noop)
        assert commit.max() >= 8 * 20
        assert (commit == commit.max()).all()
        applied = np.asarray(st.applied)
        assert (applied == commit).all()
        # identical state machines
        assert len(set(np.asarray(st.apply_chk).tolist())) == 1

    def test_ring_wraparound_with_compaction(self):
        cfg = SMALL
        st, _ = drive(cfg, 30)
        # push > log_len entries through
        n_ticks = (cfg.log_len * 3) // 16 // 2
        st, chk = drive(cfg, n_ticks, prop_count=16, state=st)
        st, _ = drive(cfg, 3, state=st)
        assert int(np.asarray(st.snap_idx).max()) > 0, "no compaction happened"
        assert int(np.asarray(st.commit).max()) >= 16 * n_ticks
        assert len(set(np.asarray(st.apply_chk).tolist())) == 1

    def test_follower_catches_up_after_crash(self):
        cfg = SMALL
        st, _ = drive(cfg, 30)
        lead = leaders_of(st)[0]
        victim = (lead + 1) % cfg.n

        def crash(t, s):
            alive = np.ones(cfg.n, bool)
            if t < 10:
                alive[victim] = False
            return alive

        st, chk = drive(cfg, 25, prop_count=8, crash=crash, state=st)
        st, _ = drive(cfg, 3, state=st)
        commit = np.asarray(st.commit)
        assert commit[victim] == commit.max()
        assert len(set(np.asarray(st.apply_chk).tolist())) == 1

    def test_slow_follower_snapshot_path(self):
        cfg = SMALL
        st, _ = drive(cfg, 30)
        lead = leaders_of(st)[0]
        victim = (lead + 1) % cfg.n
        # Down long enough that the ring compacts past its position.
        down_ticks = cfg.log_len // 16 + 8

        def crash(t, s):
            alive = np.ones(cfg.n, bool)
            if t < down_ticks:
                alive[victim] = False
            return alive

        st, chk = drive(cfg, down_ticks + 30, prop_count=16, crash=crash,
                        state=st)
        st, _ = drive(cfg, 3, state=st)
        commit = np.asarray(st.commit)
        assert int(np.asarray(st.snap_idx)[victim]) > 0
        assert commit[victim] == commit.max(), "snapshot catch-up failed"
        applied = np.asarray(st.applied)
        chks = np.asarray(st.apply_chk)
        same = np.flatnonzero(applied == applied.max())
        assert len(set(chks[same].tolist())) == 1


class TestFaults:
    def test_leader_crash_reelection(self):
        cfg = SMALL
        st, _ = drive(cfg, 30)
        first = leaders_of(st)[0]

        def crash(t, s):
            alive = np.ones(cfg.n, bool)
            alive[first] = False
            return alive

        st, chk = drive(cfg, 60, prop_count=4, crash=crash, state=st)
        new_leaders = leaders_of(st)
        live_leaders = [l for l in new_leaders if l != first]
        assert len(live_leaders) == 1
        assert np.asarray(st.commit).max() > 0

    def test_message_drops_converge(self):
        cfg = SMALL
        st, chk = drive(cfg, 150, prop_count=4, drop_rate=0.10)
        assert int(np.asarray(st.commit).max()) > 100

    def test_partition_no_split_brain_commits(self):
        cfg = SMALL
        st, _ = drive(cfg, 30)
        lead = int(leaders_of(st)[0])
        commit_before = int(np.asarray(st.commit).max())
        # Isolate the leader; propose into the majority side after
        # re-election; minority leader must not advance commit.
        minority = {lead}
        drop = np.zeros((cfg.n, cfg.n), bool)
        for i in range(cfg.n):
            for j in range(cfg.n):
                if (i in minority) != (j in minority):
                    drop[i, j] = True
        dropj = jnp.asarray(drop)
        chk = TraceChecker()
        for t in range(80):
            payloads = jnp.full((cfg.max_props,), t + 7, jnp.uint32)
            st = propose_j(st, cfg, payloads, jnp.asarray(2))
            st = step_j(st, cfg, drop=dropj)
            chk.observe(st)
        commit = np.asarray(st.commit)
        assert commit[lead] == commit_before, "isolated leader advanced commit"
        assert commit.max() > commit_before, "majority side made no progress"


class TestJit:
    def test_scan_runner_matches_eager(self):
        cfg = SMALL
        st_e, _ = drive(cfg, 25, prop_count=4)
        st0 = init_state(cfg)
        st_s, trace = run_ticks(st0, cfg, 25, prop_count=4)
        assert trace.shape == (25, 3)
        # Same deterministic inputs except payload generation differs;
        # compare consensus trajectory, not payload content.
        assert int(np.asarray(st_s.commit).max()) == \
            int(np.asarray(st_e.commit).max())
        np.testing.assert_array_equal(np.asarray(st_s.term),
                                      np.asarray(st_e.term))
        np.testing.assert_array_equal(np.asarray(st_s.role),
                                      np.asarray(st_e.role))

    def test_crash_schedule_runner(self):
        cfg = SMALL
        st0 = init_state(cfg)
        st, trace = run_ticks(st0, cfg, 200, prop_count=4, crash_every=50,
                              down_for=5)
        tr = np.asarray(trace)
        assert int(np.asarray(st.commit).max()) > 0
        # leadership was lost and re-gained at least once
        assert (tr[:, 0] == 0).any() and tr[-1, 0] >= 1


class TestScale:
    @pytest.mark.slow
    def test_64_managers(self):
        cfg = SimConfig(n=64, log_len=512, window=64, apply_batch=128,
                        max_props=64, keep=16, seed=2)
        st0 = init_state(cfg)
        st, ticks = run_until_leader(st0, cfg, max_ticks=500)
        assert int(ticks) < 500
        st, trace = run_ticks(st, cfg, 30, prop_count=64)
        st, _ = run_ticks(st, cfg, 3)  # let commit index propagate
        commit = np.asarray(st.commit)
        assert commit.max() >= 30 * 64
        # quorum of nodes fully replicated
        assert (commit == commit.max()).sum() >= 33


class TestCheckQuorumAndRejections:
    """The etcd behaviors added on top of the basic protocol: CheckQuorum
    step-down + leader lease (vendor raft.go:536-560) and candidate
    step-down on a rejection quorum (raft.go:988-1060)."""

    def _elect(self, cfg):
        st, ticks = run_until_leader(init_state(cfg), cfg, max_ticks=500)
        assert int(ticks) < 500
        return st

    def test_partitioned_leader_steps_down(self):
        cfg = SimConfig(n=5, log_len=256, window=32, apply_batch=64,
                        max_props=16, keep=8, seed=21)
        st = self._elect(cfg)
        lead = int(leaders_of(st)[0])
        # total partition of the leader: all its traffic dropped both ways
        drop = np.zeros((cfg.n, cfg.n), bool)
        drop[lead, :] = True
        drop[:, lead] = True
        dropj = jnp.asarray(drop)
        for _ in range(3 * cfg.election_tick):
            st = step_j(st, cfg, drop=dropj)
        role = np.asarray(st.role)
        assert role[lead] != LEADER, \
            "partitioned leader must step down via CheckQuorum"

    def test_leader_lease_blocks_disruptive_candidate(self):
        import dataclasses

        cfg = SimConfig(n=5, log_len=256, window=32, apply_batch=64,
                        max_props=16, keep=8, seed=23)
        st = self._elect(cfg)
        st, _ = run_ticks(st, cfg, 5, prop_count=4)
        lead = int(leaders_of(st)[0])
        term0 = int(np.asarray(st.term).max())
        # a rejoining node with an inflated term campaigns against a
        # healthy leader; leased members must ignore it
        disruptor = (lead + 1) % cfg.n
        term = st.term.at[disruptor].set(term0 + 50)
        role = st.role.at[disruptor].set(1)  # CANDIDATE
        lead_arr = st.lead.at[disruptor].set(-1)
        st = dataclasses.replace(st, term=term, role=role, lead=lead_arr)
        for _ in range(cfg.election_tick - 1):
            st = step_j(st, cfg)
        roles = np.asarray(st.role)
        assert roles[lead] == LEADER, \
            "healthy leader must survive a disruptive high-term candidate"
        assert int(np.asarray(st.term)[lead]) == term0, \
            "cluster term must not be dragged up while the lease holds"

    def test_rejection_quorum_steps_candidate_down(self):
        import dataclasses

        cfg = SimConfig(n=5, log_len=256, window=32, apply_batch=64,
                        max_props=16, keep=8, seed=25)
        st = self._elect(cfg)
        st, _ = run_ticks(st, cfg, 5, prop_count=8)
        st, _ = run_ticks(st, cfg, 3)
        # Pick a follower, WIPE its log, and force it to campaign next
        # tick. The leader is crashed so peers' leases expire and they
        # process the stale candidate's requests: their longer logs reject
        # it (log_ok fails) and the rejection quorum pushes it back to
        # follower in the SAME term it campaigned.
        lead = int(leaders_of(st)[0])
        victim = (lead + 2) % cfg.n
        st = dataclasses.replace(
            st,
            last=st.last.at[victim].set(0),
            commit=st.commit.at[victim].set(0),
            applied=st.applied.at[victim].set(0),
            apply_chk=st.apply_chk.at[victim].set(0),
            log_term=st.log_term.at[victim].set(0),
            elapsed=st.elapsed.at[victim].set(1000),
            timeout=st.timeout.at[victim].set(1),
            # free the victim from the leader lease so its campaign runs
            lead=st.lead.at[victim].set(-1),
        )
        alive = np.ones((cfg.n,), bool)
        alive[lead] = False
        alivej = jnp.asarray(alive)
        stepped_down_same_term = False
        for _ in range(4 * cfg.election_tick):
            st = step_j(st, cfg, alive=alivej)
            roles = np.asarray(st.role)
            if roles[victim] == 0 and int(np.asarray(st.vote)[victim]) == victim:
                # follower again while still having voted for itself:
                # rejection-quorum step-down, not a term catch-up
                stepped_down_same_term = True
                break
        assert stepped_down_same_term, \
            "stale candidate must stand down on a rejection quorum"
        # and the cluster still elects a proper leader afterwards
        st, ticks = run_until_leader(st, cfg, max_ticks=500)
        assert int(ticks) < 500


class TestBenchRegimeScale:
    """Invariant-checked runs at the n the BENCH actually uses (VERDICT r02
    weak #3: nothing above n=64 was ever tested off-hardware). Small
    log_len keeps CPU time sane; the [N, N] code paths are what scale."""

    @pytest.mark.slow  # tier-2: CPU-heavy, see ROADMAP tier-1 budget
    def test_1024_crash_and_drop(self):
        cfg = SimConfig(n=1024, log_len=256, window=32, apply_batch=64,
                        max_props=32, keep=16, seed=31,
                        election_tick=20)
        st0 = init_state(cfg)
        st, ticks = run_until_leader(st0, cfg, max_ticks=1000)
        assert int(ticks) < 1000
        st, trace = run_ticks(st, cfg, 60, prop_count=32, drop_rate=0.05,
                              crash_every=20, down_for=5)
        tr = np.asarray(trace)
        assert tr[:, 0].max() >= 1, "leadership must exist at some point"
        commit = np.asarray(st.commit)
        assert commit.max() > 0
        # state-machine safety at scale
        applied = np.asarray(st.applied)
        chk = np.asarray(st.apply_chk)
        by: dict = {}
        for a, c in zip(applied.tolist(), chk.tolist()):
            assert by.setdefault(a, c) == c, \
                f"checksum divergence at applied={a}"

    @pytest.mark.slow  # tier-2: CPU-heavy, see ROADMAP tier-1 budget
    def test_4096_election_and_steady_state(self):
        cfg = SimConfig(n=4096, log_len=256, window=32, apply_batch=64,
                        max_props=32, keep=16, seed=33,
                        election_tick=24)
        st0 = init_state(cfg)
        st, ticks = run_until_leader(st0, cfg, max_ticks=2000)
        assert int(ticks) < 2000
        st, _ = run_ticks(st, cfg, 8, prop_count=32)
        commit = np.asarray(st.commit)
        assert commit.max() >= 8 * 32
        # one leader per term across the fleet
        role = np.asarray(st.role)
        term = np.asarray(st.term)
        lead_terms = term[role == LEADER]
        assert len(lead_terms) == len(set(lead_terms.tolist()))


class TestLatencyMailboxes:
    """Device-mailbox wire (SURVEY §7 [N, N] in-flight slots): messages
    spend latency (+ per-message jitter) ticks in flight, one in flight per
    class per edge.  Safety invariants must hold under delay, reordering
    (jitter makes slower edges deliver after faster later sends), drops,
    and crashes; and the mailbox machinery at latency 0 must be
    decision-identical to the synchronous fast path."""

    CMP_FIELDS = ("term", "vote", "role", "lead", "elapsed", "last",
                  "commit", "applied", "snap_idx", "snap_term", "apply_chk",
                  "match", "next_", "granted", "rejected", "recent_active")

    def test_mailbox_at_latency_zero_matches_sync_path(self):
        """On a FAULT-FREE schedule the two wires coincide bit-for-bit at
        latency 0.  (Under faults they intentionally differ: the mailbox
        wire carries etcd flow control — optimistic next survives a
        dropped ack, sends are not gated on receiver liveness — while the
        sync wire re-sends from next_ every tick.  The faulty regimes are
        covered by the forced-mailbox differential gate instead.)"""
        base = dict(n=7, log_len=256, window=16, apply_batch=32,
                    max_props=16, election_tick=10, keep=8, seed=11)
        cfg_s = SimConfig(**base)
        cfg_m = SimConfig(**base, force_mailboxes=True)
        rng = np.random.default_rng(5)
        s1, s2 = init_state(cfg_s), init_state(cfg_m)
        for t in range(250):
            cnt = jnp.asarray(int(rng.integers(0, 5)), jnp.int32)
            pay = jnp.arange(cfg_s.max_props, dtype=jnp.uint32) + t * 31
            s1 = propose_j(s1, cfg_s, pay, cnt)
            s2 = propose_j(s2, cfg_m, pay, cnt)
            s1 = step_j(s1, cfg_s)
            s2 = step_j(s2, cfg_m)
            for f in self.CMP_FIELDS:
                a = np.asarray(getattr(s1, f))
                b = np.asarray(getattr(s2, f))
                assert np.array_equal(a, b), f"tick {t}: {f} diverged"

    @pytest.mark.parametrize("lat,jitter", [(1, 0), (2, 0), (3, 0), (1, 2)])
    def test_elects_and_replicates(self, lat, jitter):
        cfg = SimConfig(n=5, log_len=256, window=32, apply_batch=64,
                        max_props=16, keep=8, seed=7, election_tick=12,
                        latency=lat, latency_jitter=jitter)
        st, chk = drive(cfg, 60)
        assert len(leaders_of(st)) == 1
        st, chk = drive(cfg, 120, prop_count=8, state=st)
        commit = np.asarray(st.commit)
        assert commit.max() > 50, "replication stalled under latency"
        # every live node eventually converges near the tip
        assert commit.min() > 0

    def test_invariants_under_latency_drops_crashes(self):
        cfg = SimConfig(n=7, log_len=256, window=16, apply_batch=32,
                        max_props=8, keep=8, seed=13, election_tick=14,
                        latency=2, latency_jitter=2)
        rng = np.random.default_rng(9)

        def crash(t, st):
            return rng.random(cfg.n) > 0.08

        st, chk = drive(cfg, 400, prop_count=4, drop_rate=0.1, crash=crash)
        assert np.asarray(st.commit).max() > 0
        assert len(chk.term_leaders) >= 1

    def test_leader_crash_reelection_under_latency(self):
        cfg = SimConfig(n=5, log_len=256, window=32, apply_batch=64,
                        max_props=16, keep=8, seed=21, election_tick=12,
                        latency=2)
        st, _ = drive(cfg, 60)
        (lead,) = leaders_of(st)
        c0 = int(np.asarray(st.commit).max())

        def crash(t, st_):
            a = np.ones(cfg.n, bool)
            a[lead] = False
            return a

        st, chk = drive(cfg, 200, prop_count=4, crash=crash, state=st)
        survivors = [i for i in range(cfg.n) if i != lead]
        role = np.asarray(st.role)
        assert (role[survivors] == LEADER).sum() == 1
        assert np.asarray(st.commit)[survivors].max() > c0

    def test_stale_inflight_messages_dropped_on_term_change(self):
        """A candidate's in-flight requests must not count after it moved
        to a new term: run long enough for multiple failed campaigns under
        heavy drops and assert election safety held throughout (the
        TraceChecker in drive() raises on two leaders per term)."""
        cfg = SimConfig(n=5, log_len=256, window=16, apply_batch=32,
                        max_props=8, keep=8, seed=17, election_tick=12,
                        latency=3, latency_jitter=2)
        st, chk = drive(cfg, 500, prop_count=2, drop_rate=0.25)
        assert len(chk.term_leaders) >= 1

    def test_bench_regime_latency_invariants(self):
        cfg = SimConfig(n=256, log_len=256, window=32, apply_batch=64,
                        max_props=16, keep=16, seed=23, election_tick=20,
                        latency=1, latency_jitter=1)
        st, chk = drive(cfg, 80, prop_count=8, drop_rate=0.02)
        assert np.asarray(st.commit).max() > 0


class TestPreVoteAndTransfer:
    """PreVote (vendor campaignPreElection) + leader transfer
    (TransferLeadership/TIMEOUT_NOW) at the kernel level."""

    def _elect(self, cfg, max_ticks=400):
        st = init_state(cfg)
        for _ in range(max_ticks):
            st = step_j(st, cfg)
            if len(leaders_of(st)) == 1:
                return st
        raise AssertionError("no leader")

    def test_prevote_partitioned_node_does_not_inflate_terms(self):
        cfg = SimConfig(n=5, log_len=256, window=32, apply_batch=64,
                        max_props=16, keep=8, seed=9, election_tick=12,
                        pre_vote=True)
        st = self._elect(cfg)
        term0 = int(np.asarray(st.term).max())
        # cut node 0 off for a long time: it pre-campaigns repeatedly but
        # must never bump its term
        cut = np.zeros((cfg.n, cfg.n), bool)
        cut[0, :] = cut[:, 0] = True
        np.fill_diagonal(cut, False)
        for _ in range(120):
            st = step_j(st, cfg, drop=jnp.asarray(cut))
        assert int(np.asarray(st.term)[0]) == term0, \
            "pre-candidate inflated its term while partitioned"
        # heal: the cluster leader is NOT deposed
        for _ in range(60):
            st = step_j(st, cfg)
        assert int(np.asarray(st.term).max()) == term0
        assert len(leaders_of(st)) == 1

    @pytest.mark.parametrize("kw", [
        {}, {"pre_vote": True}, {"latency": 2},
        {"pre_vote": True, "latency": 1, "latency_jitter": 1},
    ])
    def test_transfer_moves_leadership(self, kw):
        cfg = SimConfig(n=5, log_len=256, window=32, apply_batch=64,
                        max_props=16, keep=8, seed=5, election_tick=12, **kw)
        st = self._elect(cfg)
        (lead,) = leaders_of(st)
        tgt = int((lead + 2) % cfg.n)
        st = transfer_leadership(st, cfg, int(lead), tgt)
        for _ in range(80):
            st = step_j(st, cfg)
            role = np.asarray(st.role)
            if role[tgt] == LEADER and role[lead] != LEADER:
                break
        role = np.asarray(st.role)
        assert role[tgt] == LEADER and role[lead] != LEADER

    def test_transfer_blocks_proposals_until_done_or_aborted(self):
        cfg = SimConfig(n=5, log_len=256, window=32, apply_batch=64,
                        max_props=16, keep=8, seed=6, election_tick=12)
        st = self._elect(cfg)
        (lead,) = leaders_of(st)
        st = transfer_leadership(st, cfg, int(lead), int((lead + 1) % cfg.n))
        last0 = int(np.asarray(st.last)[lead])
        st2 = propose_j(st, cfg,
                        jnp.arange(cfg.max_props, dtype=jnp.uint32),
                        jnp.asarray(4))
        assert int(np.asarray(st2.last)[lead]) == last0, \
            "transferring leader must drop proposals"

    def test_transfer_waits_for_catchup_then_completes(self):
        cfg = SimConfig(n=5, log_len=256, window=8, apply_batch=64,
                        max_props=8, keep=8, seed=8, election_tick=20,
                        latency=2)
        st = self._elect(cfg)
        (lead,) = leaders_of(st)
        tgt = int((lead + 1) % cfg.n)
        # briefly crash the target so it lags by ~2 windows, then transfer:
        # it must catch up first and then take over (TIMEOUT_NOW only fires
        # at match == last)
        alive = np.ones(cfg.n, bool)
        alive[tgt] = False
        for _ in range(2):
            st = propose_j(st, cfg,
                           jnp.arange(cfg.max_props, dtype=jnp.uint32),
                           jnp.asarray(8))
            st = step_j(st, cfg, alive=jnp.asarray(alive))
        st = transfer_leadership(st, cfg, int(lead), tgt)
        moved = False
        for _ in range(120):
            st = step_j(st, cfg)
            if np.asarray(st.role)[tgt] == LEADER:
                moved = True
                break
        assert moved, "transfer must complete after the target catches up"
        assert int(np.asarray(st.last)[tgt]) >= 16

    def test_transfer_to_deeply_lagging_target_aborts(self):
        """vendor tickHeartbeat: a transfer that cannot complete within an
        election timeout is aborted and the leader accepts proposals
        again."""
        cfg = SimConfig(n=5, log_len=256, window=8, apply_batch=64,
                        max_props=8, keep=8, seed=8, election_tick=14,
                        latency=2)
        st = self._elect(cfg)
        (lead,) = leaders_of(st)
        tgt = int((lead + 1) % cfg.n)
        alive = np.ones(cfg.n, bool)
        alive[tgt] = False
        for _ in range(10):   # ~80 entries behind: unreachable in 14 ticks
            st = propose_j(st, cfg,
                           jnp.arange(cfg.max_props, dtype=jnp.uint32),
                           jnp.asarray(8))
            st = step_j(st, cfg, alive=jnp.asarray(alive))
        st = transfer_leadership(st, cfg, int(lead), tgt)
        for _ in range(2 * cfg.election_tick):
            st = step_j(st, cfg)
        assert int(np.asarray(st.transferee)[lead]) == -1, \
            "stalled transfer must abort after an election timeout"
        assert np.asarray(st.role)[lead] == LEADER
        last0 = int(np.asarray(st.last)[lead])
        st = propose_j(st, cfg, jnp.arange(cfg.max_props, dtype=jnp.uint32),
                       jnp.asarray(4))
        assert int(np.asarray(st.last)[lead]) == last0 + 4, \
            "proposals must flow again after the abort"


class TestPipelinedAppends:
    """Windowed inflight pipelining (vendor MaxInflightMsgs + the
    probe/replicate Progress states) on the mailbox wire."""

    @pytest.mark.slow  # tier-2: CPU-heavy, see ROADMAP tier-1 budget
    def test_throughput_scales_with_depth(self):
        """The point of pipelining: K appends in flight over a lat-2 wire
        must commit ~K times faster than inflight-1 (until proposal-bound)."""
        rates = {}
        for K in (1, 2, 4):
            cfg = SimConfig(n=5, log_len=512, window=16, apply_batch=64,
                            max_props=16, keep=8, seed=3, election_tick=14,
                            latency=2, inflight=K)
            st = init_state(cfg)
            lt = None
            for t in range(300):
                st = step_j(st, cfg)
                if lt is None and len(leaders_of(st)) == 1:
                    lt = t
                if lt is not None:
                    st = propose_j(
                        st, cfg, jnp.arange(cfg.max_props, dtype=jnp.uint32),
                        jnp.asarray(16))
            rates[K] = int(np.asarray(st.commit).max()) / (300 - lt)
        assert rates[2] > 1.7 * rates[1], rates
        assert rates[4] > 2.5 * rates[1], rates

    def test_pipeline_survives_drops_and_crashes(self):
        cfg = SimConfig(n=7, log_len=256, window=16, apply_batch=32,
                        max_props=8, keep=8, seed=13, election_tick=16,
                        latency=2, latency_jitter=1, inflight=3)
        rng = np.random.default_rng(9)

        def crash(t, st):
            return rng.random(cfg.n) > 0.06

        st, chk = drive(cfg, 400, prop_count=4, drop_rate=0.1, crash=crash)
        assert np.asarray(st.commit).max() > 0
        assert len(chk.term_leaders) >= 1

    def test_rejection_backtracks_and_recovers(self):
        """A follower revived with a divergent-suffix-free gap: the leader's
        optimistic pipeline overshoots, the rejection flips the edge back
        to probe, and the follower still converges to the tip."""
        cfg = SimConfig(n=5, log_len=512, window=16, apply_batch=64,
                        max_props=16, keep=8, seed=5, election_tick=16,
                        latency=2, inflight=4)
        st = init_state(cfg)
        lt = None
        for t in range(60):
            st = step_j(st, cfg)
            if len(leaders_of(st)) == 1:
                lt = t
                break
        (lead,) = leaders_of(st)
        victim = int((lead + 1) % cfg.n)
        alive = np.ones(cfg.n, bool)
        alive[victim] = False
        for _ in range(30):
            st = propose_j(st, cfg,
                           jnp.arange(cfg.max_props, dtype=jnp.uint32),
                           jnp.asarray(8))
            st = step_j(st, cfg, alive=jnp.asarray(alive))
        for _ in range(200):
            st = step_j(st, cfg)
            if int(np.asarray(st.commit)[victim]) \
                    == int(np.asarray(st.commit).max()):
                break
        assert int(np.asarray(st.commit)[victim]) \
            == int(np.asarray(st.commit).max()), "victim never converged"


class TestAllFeaturesSoak:
    def test_everything_on_at_once(self):
        """All kernel features simultaneously — prevote, jittered latency
        mailboxes, pipelined appends, leadership transfers, crashes,
        drops, ring compaction — under per-tick safety invariants."""
        cfg = SimConfig(n=128, log_len=256, window=16, apply_batch=64,
                        max_props=16, keep=16, seed=77, election_tick=20,
                        latency=2, latency_jitter=2, inflight=3,
                        pre_vote=True)
        rng = np.random.default_rng(1)
        st = init_state(cfg)
        term_leaders: dict[int, int] = {}
        prev_commit = prev_term = None
        down_until = np.zeros(cfg.n, np.int64)
        for t in range(300):
            alive = down_until <= t
            if rng.random() < 0.05:
                v = int(rng.integers(cfg.n))
                down_until[v] = t + int(rng.integers(5, 40))
                alive[v] = False
            drop = rng.random((cfg.n, cfg.n)) < 0.05
            if t % 120 == 99:
                role = np.asarray(st.role)
                leaders = np.flatnonzero((role == LEADER) & alive)
                if len(leaders):
                    st = transfer_leadership(
                        st, cfg, int(leaders[0]), int(rng.integers(cfg.n)))
            st = propose_j(st, cfg,
                           jnp.arange(cfg.max_props, dtype=jnp.uint32)
                           + np.uint32(t * 977), jnp.asarray(8))
            st = step_j(st, cfg, alive=jnp.asarray(alive),
                        drop=jnp.asarray(drop))
            if t % 10 == 0 or t == 299:
                term = np.asarray(st.term)
                commit = np.asarray(st.commit)
                role = np.asarray(st.role)
                for lid in np.flatnonzero(
                        (role == LEADER)
                        & np.asarray(st.member).diagonal()):
                    tt = int(term[lid])
                    assert term_leaders.setdefault(tt, int(lid)) \
                        == int(lid), f"two leaders in term {tt}"
                if prev_commit is not None:
                    assert (commit >= prev_commit).all()
                    assert (term >= prev_term).all()
                prev_commit, prev_term = commit, term
                by: dict = {}
                for a, c in zip(np.asarray(st.applied).tolist(),
                                np.asarray(st.apply_chk).tolist()):
                    assert by.setdefault(a, c) == c, \
                        f"checksum divergence at applied={a}"
        assert int(np.asarray(st.commit).max()) > 200


class TestStaticMembers:
    """cfg.static_members elides every membership-view op at trace time
    (PERF.md optimization); with no conf change ever proposed it must be
    BIT-IDENTICAL to the dynamic path on every schedule — elections,
    replication, drops, crashes, both wires."""

    CMP_FIELDS = ("term", "vote", "role", "lead", "elapsed", "contact",
                  "last", "commit", "applied", "snap_idx", "snap_term",
                  "snap_chk", "apply_chk", "match", "next_", "granted",
                  "rejected", "recent_active", "pre", "transferee",
                  "pending_conf", "hup_conf", "tail_conf")

    @pytest.mark.parametrize("wire", ["sync", "mailbox"])
    def test_equivalence_under_faults(self, wire):
        base = dict(n=7, log_len=256, window=16, apply_batch=32,
                    max_props=16, election_tick=14, keep=8, seed=3)
        if wire == "mailbox":
            base.update(latency=2, latency_jitter=1, inflight=2)
        cfg_d = SimConfig(**base)
        cfg_s = SimConfig(**base, static_members=True)
        rng = np.random.default_rng(17)
        sd, ss = init_state(cfg_d), init_state(cfg_s)
        for t in range(300):
            cnt = jnp.asarray(int(rng.integers(0, 6)), jnp.int32)
            pay = jnp.arange(cfg_d.max_props, dtype=jnp.uint32) + t * 131
            alive = jnp.asarray(rng.random(cfg_d.n) > 0.05)
            drop = jnp.asarray(rng.random((cfg_d.n, cfg_d.n)) < 0.08)
            sd = propose_j(sd, cfg_d, pay, cnt, alive=alive)
            ss = propose_j(ss, cfg_s, pay, cnt, alive=alive)
            sd = step_j(sd, cfg_d, alive=alive, drop=drop)
            ss = step_j(ss, cfg_s, alive=alive, drop=drop)
            for f in self.CMP_FIELDS:
                a, b = np.asarray(getattr(sd, f)), np.asarray(getattr(ss, f))
                assert np.array_equal(a, b), f"tick {t}: {f} diverged"
        assert int(np.asarray(sd.commit).max()) > 0

    def test_transfer_equivalence(self):
        cfg_d = SimConfig(n=5, log_len=256, window=32, apply_batch=64,
                          max_props=16, keep=8, seed=9, election_tick=12)
        cfg_s = SimConfig(n=5, log_len=256, window=32, apply_batch=64,
                          max_props=16, keep=8, seed=9, election_tick=12,
                          static_members=True)
        sd, ss = init_state(cfg_d), init_state(cfg_s)
        for t in range(120):
            if t == 40 or t == 80:
                role = np.asarray(sd.role)
                leaders = np.flatnonzero(role == LEADER)
                if len(leaders):
                    lid = int(leaders[0])
                    tgt = (lid + 1) % cfg_d.n
                    sd = transfer_leadership(sd, cfg_d, lid, tgt)
                    ss = transfer_leadership(ss, cfg_s, lid, tgt)
            pay = jnp.arange(cfg_d.max_props, dtype=jnp.uint32) + t * 7
            sd = propose_j(sd, cfg_d, pay, jnp.asarray(4))
            ss = propose_j(ss, cfg_s, pay, jnp.asarray(4))
            sd = step_j(sd, cfg_d)
            ss = step_j(ss, cfg_s)
            for f in self.CMP_FIELDS:
                a, b = np.asarray(getattr(sd, f)), np.asarray(getattr(ss, f))
                assert np.array_equal(a, b), f"tick {t}: {f} diverged"
        # at least one transfer actually moved leadership
        assert len({int(x) for x in np.asarray(sd.term).tolist()}) >= 1

    def test_propose_conf_is_a_trace_time_error(self):
        from swarmkit_tpu.raft.sim import propose_conf
        cfg = SimConfig(n=5, log_len=256, window=32, apply_batch=64,
                        max_props=16, keep=8, static_members=True)
        st = init_state(cfg)
        with pytest.raises(ValueError, match="static_members"):
            propose_conf(st, cfg, 2, False)

    def test_partial_bootstrap_config_rejected(self):
        cfg = SimConfig(n=5, log_len=256, window=32, apply_batch=64,
                        max_props=16, keep=8, static_members=True)
        with pytest.raises(ValueError, match="static_members"):
            init_state(cfg, voters=[0, 1, 2])


class TestTiledLog:
    """The chunked log axis (cfg.log_chunk > 0) rewrites the [N, L] hot
    phases — append fan-out, apply+checksum, compaction — as active-window
    banded passes.  It is an OPTIMIZATION, not a semantic: every SimState
    field (including the raw ring buffers) must be bit-identical to the
    full-pass kernel on every schedule, on both wires, through elections,
    crashes, drops, transfers, and the masked full-pass fallback branch."""

    @staticmethod
    def _field_names():
        import dataclasses

        from swarmkit_tpu.raft.sim.state import SimState
        return [f.name for f in dataclasses.fields(SimState)]

    @staticmethod
    def _fused_step():
        from swarmkit_tpu.raft.sim.run import _payload_at
        return jax.jit(
            lambda st, cfg, alive, drop, cnt: step(
                st, cfg, alive=alive, drop=drop, prop_count=cnt,
                payload_fn=_payload_at),
            static_argnames=("cfg",))

    def _assert_identical(self, tag, t, golden, other, fields):
        for f in fields:
            g = np.asarray(getattr(golden, f))
            v = np.asarray(getattr(other, f))
            if not np.array_equal(g, v):
                bad = np.argwhere(g != v)[:5]
                raise AssertionError(
                    f"{tag} tick {t}: field {f} diverged at {bad.tolist()}")

    @pytest.mark.parametrize(
        "combo", ["dynamic-sync", "static-sync",
                  pytest.param("dynamic-mailbox", marks=pytest.mark.slow)])
    def test_bit_identity_under_faults(self, combo):
        """300 faulted ticks (crashes, drops, leader transfers, bursty
        fused proposals): tiled-fused and untiled-fused vs the untiled
        separate-propose ground truth, all fields compared every tick.
        dynamic-mailbox is tier-2 for the CPU wall budget."""
        from swarmkit_tpu.raft.sim.kernel import propose_dense
        from swarmkit_tpu.raft.sim.run import _payload_at

        static = combo.startswith("static")
        base = dict(n=7, log_len=1024, window=64, apply_batch=64,
                    max_props=64, keep=32, election_tick=14, seed=3,
                    static_members=static)
        if combo.endswith("mailbox"):
            base.update(latency=2, latency_jitter=1, inflight=2)
        cfg_t = SimConfig(**base, log_chunk=128)
        cfg_u = SimConfig(**base, log_chunk=0)
        assert cfg_t.tiled and not cfg_u.tiled
        step_fused = self._fused_step()
        prop_dense = jax.jit(
            lambda st, cfg, cnt, alive: propose_dense(
                st, cfg, _payload_at, cnt, alive=alive),
            static_argnames=("cfg",))
        fields = self._field_names()
        rng = np.random.default_rng(42)
        st_t, st_uf, st_us = (init_state(cfg_t), init_state(cfg_u),
                              init_state(cfg_u))
        for t in range(300):
            alive = jnp.asarray(rng.random(7) > 0.08)
            drop = jnp.asarray(rng.random((7, 7)) < 0.05)
            cnt = jnp.asarray(int(rng.integers(0, 49)), jnp.int32)
            if t % 37 == 36:
                leaders = np.flatnonzero(np.asarray(st_us.role) == LEADER)
                if len(leaders):
                    lid, tgt = int(leaders[0]), int(rng.integers(7))
                    st_t = transfer_leadership(st_t, cfg_t, lid, tgt)
                    st_uf = transfer_leadership(st_uf, cfg_u, lid, tgt)
                    st_us = transfer_leadership(st_us, cfg_u, lid, tgt)
            st_t = step_fused(st_t, cfg_t, alive, drop, cnt)
            st_uf = step_fused(st_uf, cfg_u, alive, drop, cnt)
            st_us = prop_dense(st_us, cfg_u, cnt, alive)
            st_us = step_j(st_us, cfg_u, alive=alive, drop=drop)
            self._assert_identical(f"{combo}/tiled-fused", t, st_us, st_t,
                                   fields)
            self._assert_identical(f"{combo}/untiled-fused", t, st_us,
                                   st_uf, fields)
        assert int(np.asarray(st_us.commit).max()) > 100

    def test_forced_fallback_win_and_restore_identical(self):
        """Deterministically drives the tiled kernel through its masked
        full-pass fallback branch and asserts bit-identity on every tick.

        The band cap covers the widest LEGAL append spread by construction
        (keep bounds how far a straggler can lag before the snapshot path
        takes over), so the fallback's triggers are the other `fits`
        terms: election-win ticks (any(win) — the winner stamps a noop at
        its own head) and snapshot-restore ticks (any(do_restore) — a
        revived straggler's ring is wiped).  This schedule forces both:
        the initial election, then a crash long enough that ring-pressure
        compaction (fires when last - snap_idx nears log_len) overtakes
        the victim so its revival is a restore, then a re-election after
        the leader itself crashes."""
        base = dict(n=3, log_len=1024, window=64, apply_batch=64,
                    max_props=32, keep=32, election_tick=10, seed=5)
        cfg_t = SimConfig(**base, log_chunk=128)
        cfg_u = SimConfig(**base, log_chunk=0)
        step_fused = self._fused_step()
        fields = self._field_names()
        st_t, st_u = init_state(cfg_t), init_state(cfg_u)
        no_drop = jnp.zeros((3, 3), bool)
        all_up = jnp.ones(3, bool)
        cnt8 = jnp.asarray(32, jnp.int32)

        def tick(alive, cnt, t, tag):
            nonlocal st_t, st_u
            st_t = step_fused(st_t, cfg_t, alive, no_drop, cnt)
            st_u = step_fused(st_u, cfg_u, alive, no_drop, cnt)
            self._assert_identical(tag, t, st_u, st_t, fields)

        for t in range(40):  # election win tick -> first forced fallback
            tick(all_up, cnt8, t, "warmup")
            if len(leaders_of(st_u)) and t > 5:
                break
        leaders = leaders_of(st_u)
        assert len(leaders) == 1
        victim = (int(leaders[0]) + 1) % 3
        down = all_up.at[victim].set(False)
        for t in range(45):  # leader fills the ring: pressure compaction
            tick(down, cnt8, t, "down")  # overtakes the crashed victim
        assert int(np.asarray(st_u.snap_idx).max()) \
            > int(np.asarray(st_u.last)[victim]), \
            "scenario broke: victim still reachable by plain appends"
        snap_before = int(np.asarray(st_u.snap_idx)[victim])
        for t in range(30):  # revival -> snapshot restore forced fallback
            tick(all_up, cnt8, t, "restore")
        assert int(np.asarray(st_u.snap_idx)[victim]) > snap_before, \
            "victim was never restored from snapshot"
        assert int(np.asarray(st_u.last)[victim]) \
            == int(np.asarray(st_u.last).max()), "victim never caught up"
        lead_down = all_up.at[int(leaders[0])].set(False)
        for t in range(30):  # depose the leader -> re-election fallback
            tick(lead_down, cnt8, t, "re-elect")
        assert len(leaders_of(st_u)), "no re-election happened"

    @pytest.mark.slow  # tier-2: CPU-heavy, see ROADMAP tier-1 budget
    def test_dst_cross_check_equal_bitmasks(self):
        """64 fault schedules x 100 ticks through the DST explorer, once
        per kernel variant: zero violations on stock profiles and the SAME
        per-schedule violation bitmask (and per-tick bit trace) from both
        kernels."""
        from swarmkit_tpu import dst

        base = dict(n=5, log_len=512, window=8, apply_batch=16, max_props=8,
                    keep=4, election_tick=10, seed=77)
        cfg_t = SimConfig(**base, log_chunk=128)
        cfg_u = SimConfig(**base, log_chunk=0)
        assert cfg_t.tiled and not cfg_u.tiled
        batch, names = dst.make_batch(cfg_u, ticks=100, schedules=64, seed=9)
        res_t = dst.explore(init_state(cfg_t), cfg_t, batch, profiles=names)
        res_u = dst.explore(init_state(cfg_u), cfg_u, batch, profiles=names)
        assert res_t.violating.size == 0, \
            [dst.bits_to_names(int(res_t.viol[s])) for s in res_t.violating]
        assert np.array_equal(res_t.viol, res_u.viol)
        assert np.array_equal(res_t.first_tick, res_u.first_tick)
        assert np.array_equal(res_t.bits_by_tick, res_u.bits_by_tick)


class TestTiledPeer:
    """The banded peer axis (0 < cfg.peer_chunk < n) rewrites every [N, N]
    tally/reduction — CheckQuorum heard counts, vote/pre-vote/rejection
    tallies, the commit bisection, heartbeat-ack quorum — as two-level
    hierarchical passes over [N, peer_chunk] column bands.  Integer sums
    are order-independent, so like the tiled log axis this is an
    OPTIMIZATION, not a semantic: every SimState field must be
    bit-identical to the dense kernel on every schedule, on both wires,
    through elections, conf changes, crashes, and drops."""

    PC = 8   # band width: n=16 gives two bands with boundary at column 8

    @staticmethod
    def _field_names():
        import dataclasses

        from swarmkit_tpu.raft.sim.state import SimState
        return [f.name for f in dataclasses.fields(SimState)]

    @staticmethod
    def _fused_step():
        from swarmkit_tpu.raft.sim.run import _payload_at
        return jax.jit(
            lambda st, cfg, alive, drop, cnt: step(
                st, cfg, alive=alive, drop=drop, prop_count=cnt,
                payload_fn=_payload_at),
            static_argnames=("cfg",))

    def _assert_identical(self, tag, t, golden, other, fields):
        for f in fields:
            g = np.asarray(getattr(golden, f))
            v = np.asarray(getattr(other, f))
            if not np.array_equal(g, v):
                bad = np.argwhere(g != v)[:5]
                raise AssertionError(
                    f"{tag} tick {t}: field {f} diverged at {bad.tolist()}")

    def test_validation(self):
        base = dict(n=16, log_len=256, window=32, apply_batch=64,
                    max_props=16, keep=8)
        with pytest.raises(ValueError, match="peer_chunk"):
            SimConfig(**base, peer_chunk=-8)
        with pytest.raises(ValueError, match="multiple of 8"):
            SimConfig(**base, peer_chunk=12)
        with pytest.raises(ValueError, match="divide"):
            SimConfig(**{**base, "n": 24}, peer_chunk=16)
        assert SimConfig(**base, peer_chunk=8).peer_tiled
        assert SimConfig(**base, peer_chunk=8).num_peer_chunks == 2
        assert not SimConfig(**base, peer_chunk=0).peer_tiled
        # the default chunk only tiles once n outgrows it
        assert not SimConfig(**base).peer_tiled

    @pytest.mark.parametrize(
        "combo", [pytest.param("dynamic-sync", marks=pytest.mark.slow),
                  "static-sync",
                  pytest.param("dynamic-mailbox", marks=pytest.mark.slow)])
    def test_bit_identity_under_faults(self, combo):
        """300 faulted ticks (crashes, drops, leader transfers, bursty
        fused proposals): the banded kernel vs the dense kernel, all
        SimState fields compared every tick. static-sync stays tier-1;
        the dynamic combos are tier-2 for the CPU wall budget (the DST
        equal-bitmask pin keeps dynamic banded coverage in tier-1)."""
        static = combo.startswith("static")
        base = dict(n=16, log_len=1024, window=64, apply_batch=64,
                    max_props=64, keep=32, election_tick=14, seed=3,
                    static_members=static)
        if combo.endswith("mailbox"):
            base.update(latency=2, latency_jitter=1, inflight=2)
        cfg_b = SimConfig(**base, peer_chunk=self.PC)
        cfg_d = SimConfig(**base, peer_chunk=0)
        assert cfg_b.peer_tiled and not cfg_d.peer_tiled
        step_fused = self._fused_step()
        fields = self._field_names()
        rng = np.random.default_rng(42)
        st_b, st_d = init_state(cfg_b), init_state(cfg_d)
        for t in range(300):
            alive = jnp.asarray(rng.random(16) > 0.08)
            drop = jnp.asarray(rng.random((16, 16)) < 0.05)
            cnt = jnp.asarray(int(rng.integers(0, 49)), jnp.int32)
            if t % 37 == 36:
                leaders = np.flatnonzero(np.asarray(st_d.role) == LEADER)
                if len(leaders):
                    lid, tgt = int(leaders[0]), int(rng.integers(16))
                    st_b = transfer_leadership(st_b, cfg_b, lid, tgt)
                    st_d = transfer_leadership(st_d, cfg_d, lid, tgt)
            st_b = step_fused(st_b, cfg_b, alive, drop, cnt)
            st_d = step_fused(st_d, cfg_d, alive, drop, cnt)
            self._assert_identical(f"{combo}/banded", t, st_d, st_b, fields)
        assert int(np.asarray(st_d.commit).max()) > 100

    def test_conf_change_quorum_shrink_at_band_boundary(self):
        """Removes the rows on BOTH sides of the band boundary (columns 7
        and 8 with peer_chunk=8) through committed CONF entries, then
        deposes the leader so the shrunk cluster re-elects: the membership
        fold inside each band and the hierarchical vote counts must track
        the per-row views exactly (all fields bit-identical to dense on
        every tick, and the 14-member re-election succeeds)."""
        from swarmkit_tpu.raft.sim import propose_conf

        base = dict(n=16, log_len=256, window=32, apply_batch=64,
                    max_props=16, keep=8, election_tick=10, seed=5)
        cfg_b = SimConfig(**base, peer_chunk=self.PC)
        cfg_d = SimConfig(**base, peer_chunk=0)
        fields = self._field_names()
        st_b, st_d = init_state(cfg_b), init_state(cfg_d)
        alive = jnp.ones(16, bool)

        def tick(t, tag):
            nonlocal st_b, st_d
            st_b = step_j(st_b, cfg_b, alive=alive)
            st_d = step_j(st_d, cfg_d, alive=alive)
            self._assert_identical(tag, t, st_d, st_b, fields)

        for t in range(120):
            tick(t, "elect")
            if len(leaders_of(st_d)):
                break
        (lead,) = leaders_of(st_d)
        lead = int(lead)
        # pick victims straddling the boundary, sparing the leader
        victims = [v for v in (7, 8, 9) if v != lead][:2]
        for v in victims:
            st_b = propose_conf(st_b, cfg_b, jnp.asarray(v, jnp.int32),
                                jnp.asarray(True))
            st_d = propose_conf(st_d, cfg_d, jnp.asarray(v, jnp.int32),
                                jnp.asarray(True))
            for t in range(12):
                tick(t, f"remove-{v}")
        member = np.asarray(st_d.member)
        others = [i for i in range(16) if i not in victims]
        for v in victims:
            assert not member[others, v].any(), f"removal of {v} not applied"
        # depose the leader: the 14 survivors re-elect with quorum 8,
        # counted hierarchically across the band boundary
        alive = alive.at[lead].set(False)
        for v in victims:
            alive = alive.at[v].set(False)
        for t in range(150):
            tick(t, "re-elect")
            new = [x for x in leaders_of(st_d) if x != lead]
            if new:
                break
        assert [x for x in leaders_of(st_d) if x != lead], \
            "no re-election with the shrunk quorum"

    @pytest.mark.slow  # tier-2: CPU-heavy, see ROADMAP tier-1 budget
    def test_dst_cross_check_equal_bitmasks(self):
        """64 fault schedules x 100 ticks through the DST explorer (vmap
        composes over the banded fori_loop passes), once per kernel
        variant: zero violations on stock profiles and the SAME
        per-schedule violation bitmask and per-tick bit trace."""
        from swarmkit_tpu import dst

        base = dict(n=16, log_len=64, window=8, apply_batch=16, max_props=8,
                    keep=4, election_tick=10, seed=77)
        cfg_b = SimConfig(**base, peer_chunk=self.PC)
        cfg_d = SimConfig(**base, peer_chunk=0)
        assert cfg_b.peer_tiled and not cfg_d.peer_tiled
        batch, names = dst.make_batch(cfg_d, ticks=100, schedules=64, seed=9)
        res_b = dst.explore(init_state(cfg_b), cfg_b, batch, profiles=names)
        res_d = dst.explore(init_state(cfg_d), cfg_d, batch, profiles=names)
        assert res_b.violating.size == 0, \
            [dst.bits_to_names(int(res_b.viol[s])) for s in res_b.violating]
        assert np.array_equal(res_b.viol, res_d.viol)
        assert np.array_equal(res_b.first_tick, res_d.first_tick)
        assert np.array_equal(res_b.bits_by_tick, res_d.bits_by_tick)


class TestSparseProgress:
    """The role-sparse progress lowering (0 < cfg.active_rows < n) gathers
    the rows whose node is a leader or candidate — plus rows still
    draining in-flight responses — into [A, N] slabs, runs every
    elementwise per-peer progress/fan-out update on the slabs, and
    scatters back; ticks where the active count exceeds A take a
    bit-identical dense fallback (mirroring the tiled-log contract).
    Like the other lowering levers this is an OPTIMIZATION, not a
    semantic: every SimState field except the bookkeeping active_ttl
    vector (which only exists under the sparse lowering) must be
    bit-identical to the dense elementwise kernel on every schedule,
    on all three wires, through elections, storms, transfers, and conf
    changes."""

    A = 8  # slab height: n=16 forces the fallback once >8 rows go hot

    @staticmethod
    def _field_names():
        import dataclasses

        from swarmkit_tpu.raft.sim.state import SimState
        return [f.name for f in dataclasses.fields(SimState)
                if f.name != "active_ttl"]

    _fused_step = staticmethod(TestTiledPeer._fused_step)
    _assert_identical = TestTiledPeer._assert_identical

    def test_validation(self):
        base = dict(n=16, log_len=256, window=32, apply_batch=64,
                    max_props=16, keep=8)
        with pytest.raises(ValueError, match="active_rows"):
            SimConfig(**base, active_rows=-8)
        with pytest.raises(ValueError, match="multiple of 8"):
            SimConfig(**base, active_rows=12)
        assert SimConfig(**base, active_rows=8).active_rows_on
        assert not SimConfig(**base, active_rows=0).active_rows_on
        # the default slab height only engages once n outgrows it
        assert not SimConfig(**base).active_rows_on
        assert SimConfig(**{**base, "n": 24}).active_rows_on
        st = init_state(SimConfig(**base, active_rows=8))
        assert st.active_ttl is not None and st.active_ttl.shape == (16,)
        assert init_state(SimConfig(**base, active_rows=0)).active_ttl is None

    @pytest.mark.parametrize(
        "combo", [pytest.param("dynamic-sync", marks=pytest.mark.slow),
                  "static-sync",
                  pytest.param("dynamic-mailbox", marks=pytest.mark.slow)])
    def test_bit_identity_under_faults(self, combo):
        """300 faulted ticks (crashes, drops, leader transfers, bursty
        fused proposals): the [A, N] slab kernel vs the dense elementwise
        kernel, all SimState fields compared every tick.  static-sync
        stays tier-1; the dynamic combos are tier-2 for the CPU wall
        budget."""
        static = combo.startswith("static")
        base = dict(n=16, log_len=1024, window=64, apply_batch=64,
                    max_props=64, keep=32, election_tick=14, seed=3,
                    static_members=static)
        if combo.endswith("mailbox"):
            base.update(latency=2, latency_jitter=1, inflight=2)
        cfg_s = SimConfig(**base, active_rows=self.A)
        cfg_d = SimConfig(**base, active_rows=0)
        assert cfg_s.active_rows_on and not cfg_d.active_rows_on
        step_fused = self._fused_step()
        fields = self._field_names()
        rng = np.random.default_rng(42)
        st_s, st_d = init_state(cfg_s), init_state(cfg_d)
        for t in range(300):
            alive = jnp.asarray(rng.random(16) > 0.08)
            drop = jnp.asarray(rng.random((16, 16)) < 0.05)
            cnt = jnp.asarray(int(rng.integers(0, 49)), jnp.int32)
            if t % 37 == 36:
                leaders = np.flatnonzero(np.asarray(st_d.role) == LEADER)
                if len(leaders):
                    lid, tgt = int(leaders[0]), int(rng.integers(16))
                    st_s = transfer_leadership(st_s, cfg_s, lid, tgt)
                    st_d = transfer_leadership(st_d, cfg_d, lid, tgt)
            st_s = step_fused(st_s, cfg_s, alive, drop, cnt)
            st_d = step_fused(st_d, cfg_d, alive, drop, cnt)
            self._assert_identical(f"{combo}/sparse", t, st_d, st_s, fields)
        assert int(np.asarray(st_d.commit).max()) > 100

    def test_forced_fallback_election_storm(self):
        """Deterministic fallback exercise: drop every non-self edge so
        all 16 rows time out and campaign simultaneously — the active-row
        count blows past A=8, so the sparse kernel MUST take its dense
        fallback branch while the storm lasts, and must hand back to the
        slab path bit-identically once the partition heals and the
        cluster settles on one leader."""
        from swarmkit_tpu.raft.sim.state import FOLLOWER

        base = dict(n=16, log_len=256, window=32, apply_batch=64,
                    max_props=16, keep=8, election_tick=10, seed=5,
                    static_members=True)
        cfg_s = SimConfig(**base, active_rows=self.A)
        cfg_d = SimConfig(**base, active_rows=0)
        step_fused = self._fused_step()
        fields = self._field_names()
        st_s, st_d = init_state(cfg_s), init_state(cfg_d)
        alive = jnp.ones(16, bool)
        no_drop = jnp.zeros((16, 16), bool)
        storm_drop = ~jnp.eye(16, dtype=bool)
        cnt = jnp.asarray(4, jnp.int32)

        def tick(t, tag, drop):
            nonlocal st_s, st_d
            st_s = step_fused(st_s, cfg_s, alive, drop, cnt)
            st_d = step_fused(st_d, cfg_d, alive, drop, cnt)
            self._assert_identical(tag, t, st_d, st_s, fields)

        for t in range(120):
            tick(t, "elect", no_drop)
            if len(leaders_of(st_d)):
                break
        assert len(leaders_of(st_d)) == 1
        # storm: nobody hears anybody, every row escalates to candidate
        peak = 0
        for t in range(60):
            tick(t, "storm", storm_drop)
            peak = max(peak,
                       int(np.sum(np.asarray(st_d.role) != FOLLOWER)))
        assert peak > cfg_s.active_rows, (
            f"storm never exceeded A={cfg_s.active_rows} active rows "
            f"(peak {peak}) — the fallback branch was not exercised")
        # heal: one leader again, steady state back on the slab path
        for t in range(150):
            tick(t, "heal", no_drop)
            if len(leaders_of(st_d)):
                break
        assert len(leaders_of(st_d)) == 1
        for t in range(20):
            tick(t, "steady", no_drop)

    def test_conf_change_removes_active_row_mid_tick(self):
        """Removes the LEADER — the one guaranteed-active row — through a
        committed CONF entry while replication is in flight: the row
        leaves the membership (and with it the active set) mid-stream,
        the slab gather/scatter must track the shrunk view exactly, and —
        once the shell stops the removed process (raft.go:2005, the alive
        mask) — the 15 survivors re-elect bit-identically to dense."""
        from swarmkit_tpu.raft.sim import propose_conf

        base = dict(n=16, log_len=256, window=32, apply_batch=64,
                    max_props=16, keep=8, election_tick=10, seed=5)
        cfg_s = SimConfig(**base, active_rows=self.A)
        cfg_d = SimConfig(**base, active_rows=0)
        fields = self._field_names()
        st_s, st_d = init_state(cfg_s), init_state(cfg_d)
        alive = jnp.ones(16, bool)

        def tick(t, tag):
            nonlocal st_s, st_d
            st_s = step_j(st_s, cfg_s, alive=alive)
            st_d = step_j(st_d, cfg_d, alive=alive)
            self._assert_identical(tag, t, st_d, st_s, fields)

        def stop(row):
            nonlocal alive
            alive = alive.at[row].set(False)

        for t in range(120):
            tick(t, "elect")
            if len(leaders_of(st_d)):
                break
        (lead,) = leaders_of(st_d)
        lead = int(lead)
        st_s = propose_conf(st_s, cfg_s, jnp.asarray(lead, jnp.int32),
                            jnp.asarray(True))
        st_d = propose_conf(st_d, cfg_d, jnp.asarray(lead, jnp.int32),
                            jnp.asarray(True))
        for t in range(25):
            tick(t, f"remove-leader-{lead}")
        member = np.asarray(st_d.member)
        others = [i for i in range(16) if i != lead]
        assert not member[others, lead].any(), "leader removal not applied"
        stop(lead)  # shell stops the removed manager (raft.go:2005)
        for t in range(150):
            tick(t, "re-elect")
            new = [x for x in leaders_of(st_d) if x != lead]
            if new:
                break
        assert [x for x in leaders_of(st_d) if x != lead], \
            "no re-election after removing the leader row"

    @pytest.mark.slow  # tier-2: CPU-heavy, see ROADMAP tier-1 budget
    def test_dst_cross_check_equal_bitmasks(self):
        """64 fault schedules x 100 ticks through the DST explorer (vmap
        lowers the sparse/dense lax.cond to a select, so BOTH branches
        run on every schedule), once per progress lowering: zero
        violations on stock profiles and the SAME per-schedule violation
        bitmask and per-tick bit trace."""
        from swarmkit_tpu import dst

        base = dict(n=16, log_len=64, window=8, apply_batch=16, max_props=8,
                    keep=4, election_tick=10, seed=77)
        cfg_s = SimConfig(**base, active_rows=self.A)
        cfg_d = SimConfig(**base, active_rows=0)
        assert cfg_s.active_rows_on and not cfg_d.active_rows_on
        batch, names = dst.make_batch(cfg_d, ticks=100, schedules=64, seed=9)
        res_s = dst.explore(init_state(cfg_s), cfg_s, batch, profiles=names)
        res_d = dst.explore(init_state(cfg_d), cfg_d, batch, profiles=names)
        assert res_s.violating.size == 0, \
            [dst.bits_to_names(int(res_s.viol[s])) for s in res_s.violating]
        assert np.array_equal(res_s.viol, res_d.viol)
        assert np.array_equal(res_s.first_tick, res_d.first_tick)
        assert np.array_equal(res_s.bits_by_tick, res_d.bits_by_tick)

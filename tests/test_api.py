"""Data-model round-trip tests (reference: api/ generated code behavior)."""

from swarmkit_tpu.api import (
    Annotations, ClusterSpec, Mode, Node, NodeRole, NodeSpec, ReplicatedService,
    Service, ServiceSpec, StoreAction, StoreActionKind, Task, TaskSpec,
    TaskState, TaskStatus, InternalRaftRequest, Snapshot, StoreSnapshot,
)
from swarmkit_tpu.api.specs import ContainerSpec, RestartPolicy
from swarmkit_tpu.api.objects import kind_of


def _service() -> Service:
    return Service(
        id="svc1",
        spec=ServiceSpec(
            annotations=Annotations(name="web", labels={"tier": "frontend"}),
            task=TaskSpec(
                container=ContainerSpec(image="nginx:latest", env=["A=1"]),
                restart=RestartPolicy(delay=1.5),
            ),
            mode=Mode.REPLICATED,
            replicated=ReplicatedService(replicas=3),
        ),
    )


def test_roundtrip_service():
    s = _service()
    data = s.to_dict()
    s2 = Service.from_dict(data)
    assert s2 == s
    assert s2.spec.task.container.image == "nginx:latest"
    assert s2.spec.replica_count() == 3


def test_encode_decode_bytes_stable():
    s = _service()
    raw = s.encode()
    assert Service.decode(raw) == s
    assert s.encode() == raw  # canonical


def test_copy_is_deep():
    s = _service()
    c = s.copy()
    c.spec.annotations.labels["tier"] = "backend"
    assert s.spec.annotations.labels["tier"] == "frontend"


def test_task_state_ordering():
    assert TaskState.NEW < TaskState.PENDING < TaskState.ASSIGNED
    assert TaskState.RUNNING < TaskState.COMPLETE
    assert TaskState.ORPHANED == 832
    # gaps of 64 like the reference enum
    assert TaskState.PENDING == 64 and TaskState.RUNNING == 448


def test_store_action_roundtrip():
    t = Task(id="t1", service_id="svc1", slot=2,
             status=TaskStatus(state=TaskState.RUNNING),
             desired_state=int(TaskState.RUNNING))
    a = StoreAction.make(StoreActionKind.CREATE, t)
    req = InternalRaftRequest(id=7, actions=[a])
    req2 = InternalRaftRequest.decode(req.encode())
    obj = req2.actions[0].object()
    assert isinstance(obj, Task) and obj.slot == 2
    assert obj.status.state == TaskState.RUNNING


def test_kind_of():
    assert kind_of(Node(id="n")) == "node"
    assert kind_of(_service()) == "service"


def test_snapshot_roundtrip():
    snap = Snapshot(version=42, store=StoreSnapshot(
        objects={"node": [Node(id="n1", spec=NodeSpec(
            desired_role=NodeRole.MANAGER)).to_dict()]}))
    snap2 = Snapshot.decode(snap.encode())
    assert snap2.version == 42
    n = Node.from_dict(snap2.store.objects["node"][0])
    assert n.spec.desired_role == NodeRole.MANAGER


def test_cluster_spec_defaults():
    cs = ClusterSpec()
    assert cs.raft.snapshot_interval == 10000
    assert cs.raft.election_tick == 10
    assert cs.dispatcher.heartbeat_period == 5.0


def test_fingerprint_stable_across_hash_seeds():
    """fingerprint() feeds restart history and scheduler taints that
    survive WAL/snapshot restore into a NEW process, so it must not ride
    on salted hash() — identical specs must fingerprint identically under
    any PYTHONHASHSEED."""
    import os
    import subprocess
    import sys

    s = _service()
    fp = s.spec.fingerprint()
    assert fp == s.spec.copy().fingerprint()

    prog = (
        "from tests.test_api import _service; "
        "print(_service().spec.fingerprint())"
    )
    repo = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
    seen = set()
    for seed in ("0", "1", "424242"):
        env = dict(os.environ, PYTHONHASHSEED=seed, PYTHONPATH=repo)
        out = subprocess.run([sys.executable, "-c", prog], env=env, cwd=repo,
                             capture_output=True, text=True, check=True)
        seen.add(int(out.stdout.strip()))
    assert seen == {fp}, seen

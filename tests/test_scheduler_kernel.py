"""Jitted scheduler kernel vs the host Pipeline: bit-identical decisions.

The kernel (manager/scheduler/kernel.py) must make EXACTLY the choices
``_schedule_group``'s host loop makes — same node per task in FIFO order
— across randomized node fleets, resource reservations, constraints,
max-replicas caps, spread preferences, failure taints and pre-existing
load.  The host Pipeline stays the oracle; any mismatch is a kernel bug
by definition.  Uncovered encodings (named generic resources, multi-level
spread) must return None and fall back to the host path.
"""

import random

from swarmkit_tpu.api import (
    Annotations, NodeAvailability, NodeDescription, NodeResources, NodeSpec,
    NodeState, Placement, Platform, Resources,
    ResourceRequirements, Task, TaskSpec, TaskState, TaskStatus,
)
from swarmkit_tpu.api.objects import Node, NodeStatus
from swarmkit_tpu.manager.scheduler.nodeinfo import NodeInfo
from swarmkit_tpu.manager.scheduler.scheduler import Scheduler
from swarmkit_tpu.metrics import catalog as obs_catalog
from swarmkit_tpu.metrics.registry import MetricsRegistry
from swarmkit_tpu.store import MemoryStore
from tests.conftest import async_test

GIG = 1 << 30


def _node(i, cpus, mem, zone, ready=True, generic=None, named=None):
    return Node(
        id=f"n{i:02d}",
        spec=NodeSpec(annotations=Annotations(name=f"n{i:02d}",
                                              labels={"zone": zone}),
                      availability=NodeAvailability.ACTIVE),
        description=NodeDescription(
            hostname=f"h{i}",
            platform=Platform(architecture="x86_64", os="linux"),
            resources=NodeResources(nano_cpus=cpus, memory_bytes=mem,
                                    generic=dict(generic or {}),
                                    generic_named=dict(named or {}))),
        status=NodeStatus(state=NodeState.READY if ready
                          else NodeState.DOWN),
    )


def _task(i, service="svc", cpus=0, mem=0, constraints=None, prefs=None,
          max_replicas=0, generic=None):
    spec = TaskSpec()
    if cpus or mem or generic:
        spec.resources = ResourceRequirements(
            reservations=Resources(nano_cpus=cpus, memory_bytes=mem,
                                   generic=dict(generic or {})))
    if constraints or prefs or max_replicas:
        spec.placement = Placement(constraints=constraints or [],
                                   preferences=prefs or [],
                                   max_replicas=max_replicas)
    return Task(id=f"t{i:03d}", service_id=service, slot=i, spec=spec,
                status=TaskStatus(state=TaskState.PENDING),
                desired_state=int(TaskState.RUNNING))


def _running(i, node_id, service):
    t = _task(1000 + i, service=service)
    t.node_id = node_id
    t.status.state = TaskState.RUNNING
    return t


def _sched(use_kernel: bool) -> Scheduler:
    return Scheduler(MemoryStore(), obs=MetricsRegistry(),
                     use_kernel=use_kernel)


def _random_world(rng):
    """One randomized (nodes, existing tasks, group) scenario; returns a
    builder so host and kernel schedulers get IDENTICAL independent
    copies (scheduling mutates NodeInfo)."""
    n_nodes = rng.randint(1, 12)
    zones = ["a", "b", "c"]
    nodes = []
    for i in range(n_nodes):
        nodes.append(dict(
            i=i,
            cpus=rng.choice([1, 2, 4, 8]) * 1_000_000_000,
            mem=rng.choice([1, 2, 4, 8]) * GIG,
            zone=rng.choice(zones),
            ready=rng.random() > 0.15,
            n_existing=rng.randint(0, 3),
        ))
    service = rng.choice(["svc", "svc", "svc", ""])
    t_kw = dict(
        service=service,
        cpus=rng.choice([0, 0, 500_000_000, 1_500_000_000, 3_000_000_000]),
        mem=rng.choice([0, 0, GIG // 2, 2 * GIG]),
        constraints=rng.choice(
            [None, None, ["node.labels.zone==a"],
             ["node.labels.zone!=b"]]),
        prefs=rng.choice([None, None, ["spread=node.labels.zone"]]),
        max_replicas=rng.choice([0, 0, 0, 1, 2]),
    )
    n_tasks = rng.randint(1, 16)
    taint_nodes = [nd["i"] for nd in nodes if rng.random() < 0.2]

    def build(sched: Scheduler) -> list:
        tasks = [_task(i, **t_kw) for i in range(n_tasks)]
        fkey = NodeInfo.failure_key(tasks[0])
        now = sched.clock.now()
        for nd in nodes:
            existing = {}
            for j in range(nd["n_existing"]):
                et = _running(nd["i"] * 10 + j, f"n{nd['i']:02d}",
                              ["svc", "other"][j % 2])
                existing[et.id] = et
            info = NodeInfo(_node(nd["i"], nd["cpus"], nd["mem"],
                                  nd["zone"], nd["ready"]), existing)
            if nd["i"] in taint_nodes:
                # enough recent failures to taint this service's key
                for _ in range(4):
                    info.recent_failures.setdefault(fkey, []).append(now)
            sched.node_set.add_or_update(info)
        return tasks

    return build


def _decide(sched: Scheduler, tasks: list) -> list[tuple[str, str]]:
    return [(t.id, node_id) for t, node_id, _ in
            sched._schedule_group(tasks)]


@async_test
async def test_randomized_differential_bit_identical():
    rng = random.Random(1234)
    kernel_used = 0
    for trial in range(60):
        build = _random_world(rng)
        host, kern = _sched(False), _sched(True)
        tasks_h = build(host)
        tasks_k = build(kern)
        dh = _decide(host, tasks_h)
        dk = _decide(kern, tasks_k)
        assert dh == dk, (f"trial {trial}: host {dh} != kernel {dk}")
        kernel_used += int(obs_catalog.get(
            kern.obs, "swarm_sched_kernel_groups_total")
            .labels(path="kernel").value)
    # the suite must actually exercise the device path, not fall back
    # everywhere
    assert kernel_used >= 30, f"kernel path ran only {kernel_used}/60 trials"


@async_test
async def test_kernel_resource_exhaustion_matches_host():
    """More tasks than fleet capacity: the same prefix places, the same
    tail stays unplaced, on both paths."""
    host, kern = _sched(False), _sched(True)
    for s in (host, kern):
        for i in range(3):
            s.node_set.add_or_update(NodeInfo(
                _node(i, 2_000_000_000, 2 * GIG, "a"), {}))
    tasks = [_task(i, cpus=1_000_000_000, mem=GIG) for i in range(10)]
    dh = _decide(host, list(tasks))
    dk = _decide(kern, [t.copy() for t in tasks])
    assert dh == dk
    assert len(dh) == 6  # 2 per node fit


@async_test
async def test_kernel_spread_tie_break_matches_host():
    host, kern = _sched(False), _sched(True)
    for s in (host, kern):
        for i, zone in enumerate(["a", "a", "b", "b", "c"]):
            s.node_set.add_or_update(NodeInfo(
                _node(i, 4_000_000_000, 4 * GIG, zone), {}))
    tasks = [_task(i, prefs=["spread=node.labels.zone"])
             for i in range(11)]
    dh = _decide(host, list(tasks))
    dk = _decide(kern, [t.copy() for t in tasks])
    assert dh == dk and len(dh) == 11


@async_test
async def test_kernel_falls_back_on_named_generic_and_multispread():
    """Uncovered encodings return None and the host path decides — with
    the fallback counter bumped, never a wrong kernel answer."""
    from swarmkit_tpu.manager.scheduler import kernel as mod

    # named generic resources (discrete device ids) are not encodable
    node = _node(0, 4_000_000_000, 4 * GIG, "a",
                 named={"gpu": ["gpu0", "gpu1"]})
    info = NodeInfo(node, {})
    t = _task(0, generic={"gpu": 1})
    enc = mod.encode_group(t, [], [info], NodeInfo.failure_key(t), 0.0)
    assert enc is None

    t2 = _task(1)
    enc2 = mod.encode_group(
        t2, ["spread=node.labels.zone", "spread=node.labels.rack"],
        [NodeInfo(_node(1, 4_000_000_000, 4 * GIG, "a"), {})],
        NodeInfo.failure_key(t2), 0.0)
    assert enc2 is None

    kern = _sched(True)
    kern.node_set.add_or_update(info)
    d = _decide(kern, [t])
    assert d == [("t000", "n00")]
    assert int(obs_catalog.get(
        kern.obs, "swarm_sched_kernel_groups_total")
        .labels(path="host").value) == 1


@async_test
async def test_kernel_empty_node_set():
    kern = _sched(True)
    assert _decide(kern, [_task(0)]) == []

"""Sharded-execution correctness for the batched raft kernel (VERDICT r02
missing #3): the kernel sharded over the 8-virtual-device CPU mesh must
(a) produce BIT-IDENTICAL results to the unsharded run, (b) actually lower
to cross-device collectives (not 8 replicas), and (c) handle LOG-DRIVEN
membership changes (committed CONF entries flipping per-row `member`
views, VERDICT r03 missing #1) mid-run with re-election.

Reference parity bar: membership + replication scenarios of
manager/state/raft/raft_test.go:63-1025 and the conf-change apply path
raft.go:1939/membership/cluster.go:185, here at the device-kernel level.
"""

import numpy as np
import pytest

import jax
import jax.numpy as jnp

from swarmkit_tpu.parallel import row_mesh, shard_rows, state_shardings
from swarmkit_tpu.raft.sim import (
    LEADER, SimConfig, committed_entries, init_state, propose, run_ticks,
    run_until_leader, step,
)
from swarmkit_tpu.raft.sim.kernel import propose_conf, propose_dense
from swarmkit_tpu.raft.sim.run import _payload_at, _payloads

CFG = SimConfig(n=64, log_len=128, window=16, apply_batch=32, max_props=16,
                keep=8, seed=11)


def _leaves(state):
    return jax.tree.leaves(state)


def assert_states_identical(a, b):
    for la, lb, path in zip(
            _leaves(a), _leaves(b),
            [p for p, _ in jax.tree_util.tree_flatten_with_path(a)[0]]):
        na, nb = np.asarray(la), np.asarray(lb)
        assert na.dtype == nb.dtype
        assert (na == nb).all(), f"leaf {path} diverged"


class TestShardedEquivalence:
    def test_steady_state_bit_identical(self):
        mesh = row_mesh(CFG.n)
        assert len(mesh.devices.ravel()) == 8

        unsharded, tr_u = run_ticks(init_state(CFG), CFG, 50, prop_count=8)
        sharded_in = shard_rows(init_state(CFG), mesh)
        sharded, tr_s = run_ticks(sharded_in, CFG, 50, prop_count=8)

        assert_states_identical(unsharded, sharded)
        assert (np.asarray(tr_u) == np.asarray(tr_s)).all()
        assert int(committed_entries(sharded)) > 0

    def test_faulty_run_bit_identical(self):
        """Crash + drop schedules exercise every masked branch."""
        mesh = row_mesh(CFG.n)
        kw = dict(prop_count=4, drop_rate=0.1, crash_every=10, down_for=3)
        unsharded, _ = run_ticks(init_state(CFG), CFG, 60, **kw)
        sharded, _ = run_ticks(shard_rows(init_state(CFG), mesh), CFG, 60,
                               **kw)
        assert_states_identical(unsharded, sharded)

    def test_output_shardings_preserved(self):
        """The stepped state stays row-sharded — the scan doesn't silently
        gather everything to one device."""
        mesh = row_mesh(CFG.n)
        state = shard_rows(init_state(CFG), mesh)
        out, _ = run_ticks(state, CFG, 4, prop_count=2)
        spec = out.log_term.sharding.spec
        assert spec and spec[0] == "managers", \
            f"log_term lost its row sharding: {spec}"


    @pytest.mark.slow  # tier-2: CPU-heavy, see ROADMAP tier-1 budget
    def test_banded_peer_sharded_bit_identical(self):
        """The banded peer reductions (cfg.peer_chunk) compose with row
        sharding: each [N, peer_chunk] column band is dynamic-sliced from
        a row-sharded [N, N] matrix (device-local — rows stay put, the
        column axis is replicated) and the [N, num_peer_chunks] partials
        stay row-sharded.  Banded-sharded, banded-unsharded, and
        dense-unsharded must agree on every field under faults."""
        import dataclasses as _dc

        cfg_b = _dc.replace(CFG, peer_chunk=8)
        cfg_d = _dc.replace(CFG, peer_chunk=0)
        assert cfg_b.peer_tiled and not cfg_d.peer_tiled
        mesh = row_mesh(cfg_b.n)
        kw = dict(prop_count=4, drop_rate=0.1, crash_every=10, down_for=3)
        dense, _ = run_ticks(init_state(cfg_d), cfg_d, 60, **kw)
        banded, _ = run_ticks(init_state(cfg_b), cfg_b, 60, **kw)
        sharded, _ = run_ticks(shard_rows(init_state(cfg_b), mesh), cfg_b,
                               60, **kw)
        assert_states_identical(dense, banded)
        assert_states_identical(dense, sharded)
        spec = sharded.log_term.sharding.spec
        assert spec and spec[0] == "managers", \
            f"banded run lost its row sharding: {spec}"
        assert int(committed_entries(sharded)) > 0


class TestCollectiveLowering:
    def test_step_hlo_contains_cross_device_collectives(self):
        """VERDICT r02 weak #6: prove the sharded step is collective-based.
        The append fan-out's row-broadcast (log_term[src]) and the
        sender-axis reductions must produce cross-partition ops."""
        mesh = row_mesh(CFG.n)
        state = shard_rows(init_state(CFG), mesh)
        shardings = state_shardings(mesh, state)
        fn = jax.jit(lambda st: step(st, CFG), in_shardings=(shardings,),
                     out_shardings=shardings)
        hlo = fn.lower(state).compile().as_text()
        assert any(op in hlo for op in
                   ("all-to-all", "all-gather", "all-reduce",
                    "collective-permute", "reduce-scatter")), \
            "sharded step HLO contains no cross-device collectives"


@pytest.mark.slow
class TestDeviceConfChange:
    """Membership flows through the replicated log on the device kernel:
    propose_conf appends a CONF entry, commit + apply flip each row's OWN
    member view (kernel Phase E), and every quorum computation follows the
    per-row views (reference processConfChange raft.go:1939).  Slow-marked
    for the tier-1 wall budget: the non-sharded conf-change pins in
    test_raft_sim.py keep the semantics in tier-1."""

    def _elect(self, cfg, state):
        state, ticks = run_until_leader(state, cfg, max_ticks=500)
        lm = np.asarray(state.role == LEADER) \
            & np.asarray(state.member).diagonal()
        assert lm.any()
        return state

    def _leader(self, state):
        return int(np.flatnonzero(
            np.asarray(state.role == LEADER)
            & np.asarray(state.member).diagonal())[0])

    def test_remove_leader_via_log_reelects_and_commits(self):
        """The leader proposes its own removal; once every row applies the
        committed CONF entry, the cluster's views exclude it.  The node
        shell then stops the removed process (raft.go:2005) — modeled by
        the alive mask — and the remaining 7 elect with quorum 4."""
        cfg = SimConfig(n=8, log_len=128, window=16, apply_batch=32,
                        max_props=16, keep=8, seed=5)
        state = self._elect(cfg, init_state(cfg))
        lead = self._leader(state)

        state = propose_conf(state, cfg, jnp.asarray(lead, jnp.int32),
                             jnp.asarray(True))
        for _ in range(6):
            state = step(state, cfg)
        member = np.asarray(state.member)
        assert not member[:, lead].any(), "removal did not reach every view"

        # shell stops the removed manager; others re-elect without it
        alive = jnp.ones((cfg.n,), bool).at[lead].set(False)
        for _ in range(80):
            state = step(state, cfg, alive=alive)
            role = np.asarray(state.role)
            others = [i for i in range(cfg.n) if i != lead]
            if (role[others] == LEADER).any():
                break
        new_lead = self._leader(state)
        assert new_lead != lead

        base = int(committed_entries(state))
        state = propose(state, cfg, _payloads(cfg, state.tick, 8),
                        jnp.asarray(8, jnp.int32))
        state = step(state, cfg, alive=alive)
        state = step(state, cfg, alive=alive)
        assert int(committed_entries(state)) >= base + 8

    def test_bootstrap_subset_quorum(self):
        """A 3-voter bootstrap among 8 rows elects within the subset with
        quorum 2 (non-members never campaign)."""
        cfg = SimConfig(n=8, log_len=128, window=16, apply_batch=32,
                        max_props=16, keep=8, seed=9)
        state = init_state(cfg, voters=range(3))
        state = self._elect(cfg, state)
        lead_mask = np.asarray(state.role == LEADER) \
            & np.asarray(state.member).diagonal()
        assert lead_mask[:3].any() and not lead_mask[3:].any()
        state = propose(state, cfg, _payloads(cfg, state.tick, 4),
                        jnp.asarray(4, jnp.int32))
        state = step(state, cfg)
        state = step(state, cfg)
        assert int(committed_entries(state)) >= 4

    def test_joiner_catches_up_via_log_add(self):
        """A row outside the bootstrap config is added by a committed CONF
        entry after the ring compacted past its position: the leader ships
        a snapshot (carrying the config), the joiner catches up, and its
        own view finally includes itself."""
        cfg = SimConfig(n=8, log_len=64, window=8, apply_batch=16,
                        max_props=8, keep=4, seed=13)
        joiner = 7
        state = init_state(cfg, voters=range(7))
        state = self._elect(cfg, state)
        # commit enough to force ring compaction past the joiner's log
        for _ in range(30):
            state = propose(state, cfg, _payloads(cfg, state.tick, 8),
                            jnp.asarray(8, jnp.int32))
            state = step(state, cfg)
        assert int(np.asarray(state.snap_idx).max()) > 0
        assert not bool(np.asarray(state.member)[:, joiner].any())

        state = propose_conf(state, cfg, jnp.asarray(joiner, jnp.int32),
                             jnp.asarray(False))
        for _ in range(30):
            state = step(state, cfg)
        member = np.asarray(state.member)
        assert member[:, joiner].all(), "add did not reach every view"
        assert member[joiner, joiner], "joiner never learned it was added"
        commit = np.asarray(state.commit)
        applied = np.asarray(state.applied)
        chk = np.asarray(state.apply_chk)
        assert applied[joiner] >= commit.max() - cfg.max_props
        by: dict = {}
        for a, c in zip(applied.tolist(), chk.tolist()):
            assert by.setdefault(a, c) == c, "checksum divergence on join"

    def test_one_conf_in_flight(self):
        """While a CONF entry is in flight, a second conf proposal degrades
        to an empty normal entry (core stepLeader MsgProp rule); after the
        first applies, a new one is accepted."""
        cfg = SimConfig(n=8, log_len=128, window=16, apply_batch=32,
                        max_props=16, keep=8, seed=21)
        state = init_state(cfg)
        state = self._elect(cfg, state)
        lead = self._leader(state)
        state = propose_conf(state, cfg, jnp.asarray(6, jnp.int32),
                             jnp.asarray(True))
        assert bool(np.asarray(state.pending_conf)[lead])
        # second proposal before the first commits: degraded
        state = propose_conf(state, cfg, jnp.asarray(5, jnp.int32),
                             jnp.asarray(True))
        for _ in range(8):
            state = step(state, cfg)
        member = np.asarray(state.member)
        # every row but the victim applies the removal (the victim itself
        # may never learn: once the leader's view drops it, appends stop —
        # etcd behavior; the shell shuts the node down, raft.go:2005)
        others = [i for i in range(cfg.n) if i != 6]
        assert not member[others, 6].any()     # first removal applied
        assert member[:, 5].all()              # second was degraded
        assert not bool(np.asarray(state.pending_conf)[lead])
        # now a fresh conf proposal is accepted
        state = propose_conf(state, cfg, jnp.asarray(5, jnp.int32),
                             jnp.asarray(True))
        for _ in range(8):
            state = step(state, cfg)
        # rows still in the cluster apply it; 5 itself and the previously
        # removed 6 are cut off and keep their frozen views
        keep = [i for i in range(cfg.n) if i not in (5, 6)]
        assert not np.asarray(state.member)[keep, 5].any()


class TestProposeDense:
    def test_dense_equals_batched_propose(self):
        """propose_dense(payload_fn) must be decision-identical to
        propose(payloads) with the same generated batch."""
        cfg = SimConfig(n=8, log_len=64, window=8, apply_batch=16,
                        max_props=8, keep=4, seed=3)
        state = init_state(cfg)
        state, _ = run_until_leader(state, cfg, max_ticks=300)
        for count in (1, 5, 8):
            a = propose(state, cfg, _payloads(cfg, state.tick, count),
                        jnp.asarray(count, jnp.int32))
            b = propose_dense(state, cfg, _payload_at,
                              jnp.asarray(count, jnp.int32))
            assert_states_identical(a, b)
            state = step(a, cfg)


@pytest.mark.slow
class TestShardedMailboxWire:
    """The mailbox wire's [N, N, K] in-flight state shards over the row
    mesh like the rest of SimState (leading axis = managers).  Slow-marked
    for the tier-1 wall budget: sharded bit-identity stays tier-1 via
    TestShardedEquivalence / TestShardedStaticMembers / TestContactLease,
    and the mailbox wire itself via the test_raft_sim.py pins."""

    MCFG = SimConfig(n=64, log_len=128, window=16, apply_batch=32,
                     max_props=16, keep=8, seed=19, election_tick=16,
                     latency=2, latency_jitter=1, inflight=3, pre_vote=True)

    def test_mailbox_run_bit_identical(self):
        mesh = row_mesh(self.MCFG.n)
        unsharded, tr_u = run_ticks(init_state(self.MCFG), self.MCFG, 60,
                                    prop_count=8, drop_rate=0.05)
        sharded_in = shard_rows(init_state(self.MCFG), mesh)
        sharded, tr_s = run_ticks(sharded_in, self.MCFG, 60,
                                  prop_count=8, drop_rate=0.05)
        assert_states_identical(unsharded, sharded)
        assert (np.asarray(tr_u) == np.asarray(tr_s)).all()
        assert int(committed_entries(sharded)) > 0

    def test_transfer_on_sharded_mailbox_wire(self):
        from swarmkit_tpu.raft.sim import transfer_leadership

        mesh = row_mesh(self.MCFG.n)
        st = shard_rows(init_state(self.MCFG), mesh)
        st, ticks = run_until_leader(st, self.MCFG, max_ticks=800)
        assert int(ticks) < 800
        lead = int(np.flatnonzero(
            np.asarray(st.role == LEADER)
            & np.asarray(st.member).diagonal())[0])
        tgt = (lead + 1) % self.MCFG.n
        st = transfer_leadership(st, self.MCFG, lead, tgt)
        moved = False
        for _ in range(120):
            st, _ = run_ticks(st, self.MCFG, 1)
            if np.asarray(st.role)[tgt] == LEADER:
                moved = True
                break
        assert moved, "transfer never completed on the sharded wire"

    def test_mailbox_step_hlo_contains_collectives(self):
        mesh = row_mesh(self.MCFG.n)
        st = shard_rows(init_state(self.MCFG), mesh)
        shardings = state_shardings(mesh, st)
        lowered = jax.jit(
            lambda s: step(s, self.MCFG),
            in_shardings=(shardings,), out_shardings=shardings,
        ).lower(st)
        hlo = lowered.compile().as_text()
        assert any(tok in hlo for tok in
                   ("all-reduce", "all-gather", "collective-permute",
                    "all-to-all", "reduce-scatter")), \
            "sharded mailbox step must lower to cross-device collectives"


class TestContactLease:
    """The CheckQuorum lease measures LEADER CONTACT, not the election
    timer (core.contact_elapsed rationale): after total leader loss with
    survivors at EXACTLY quorum, elections must still converge — under
    etcd-3.1's campaign-reset lease this regime livelocks permanently
    whenever any survivor's deterministic timeout equals election_tick."""

    def test_exact_quorum_survivorship_elects(self):
        cfg = SimConfig(n=16, log_len=256, window=16, apply_batch=64,
                        max_props=32, keep=16, seed=42, pre_vote=True)
        state = init_state(cfg)
        state, ticks = run_until_leader(state, cfg, max_ticks=500)
        # commit a little traffic, then kill 7 rows incl. the leader —
        # 9 survivors == quorum of 16
        lead = int(np.flatnonzero(
            np.asarray(state.role == LEADER)
            & np.asarray(state.member).diagonal())[0])
        kill = ([lead] + [i for i in range(cfg.n) if i != lead])[:7]
        alive = jnp.ones((cfg.n,), bool).at[jnp.asarray(kill)].set(False)
        elected = False
        for _ in range(150):
            state = step(state, cfg, alive=alive)
            role = np.asarray(state.role)
            if any(role[i] == LEADER for i in range(cfg.n) if i not in kill):
                elected = True
                break
        assert elected, "exact-quorum survivors failed to elect (lease livelock)"


class TestShardedStaticMembers:
    """The bench's static-members specialization sharded over the mesh:
    bit-identical to the unsharded static run AND to the sharded dynamic
    run (no conf changes), and the compiled program still contains
    cross-device collectives.  Guards the exact configuration bench.py
    compiles on TPU hardware."""

    CFG_S = SimConfig(n=64, log_len=128, window=16, apply_batch=32,
                      max_props=16, keep=8, seed=11, static_members=True)

    def test_sharded_static_bit_identical(self):
        mesh = row_mesh(self.CFG_S.n)
        unsharded, tr_u = run_ticks(init_state(self.CFG_S), self.CFG_S, 50,
                                    prop_count=8)
        sharded, tr_s = run_ticks(shard_rows(init_state(self.CFG_S), mesh),
                                  self.CFG_S, 50, prop_count=8)
        assert_states_identical(unsharded, sharded)
        assert (np.asarray(tr_u) == np.asarray(tr_s)).all()

        # ... and static == dynamic on the same sharded schedule
        dynamic, _ = run_ticks(shard_rows(init_state(CFG), mesh), CFG, 50,
                               prop_count=8)
        for f in ("term", "role", "last", "commit", "applied", "apply_chk"):
            assert (np.asarray(getattr(sharded, f))
                    == np.asarray(getattr(dynamic, f))).all(), f

    def test_sharded_static_lowering_has_collectives(self):
        mesh = row_mesh(self.CFG_S.n)
        st = shard_rows(init_state(self.CFG_S), mesh)
        lowered = jax.jit(
            step, static_argnames=("cfg",)).lower(st, self.CFG_S)
        hlo = lowered.compile().as_text()
        assert ("all-reduce" in hlo or "all-gather" in hlo
                or "all-to-all" in hlo or "collective" in hlo), \
            "sharded static step lowered without cross-device collectives"


class TestMultiHostMesh:
    """The multi-host (DCN x ICI) layout: the manager axis sharded over a
    2-D hosts x chips mesh, hosts outermost.  On the 8-virtual-device CPU
    backend this runs as 2 hosts x 4 chips; the kernel itself is layout-
    oblivious, so results must be bit-identical to the unsharded and 1-D
    runs (the scaling-book outer-DCN/inner-ICI recipe; reference analog:
    manager raft members spanning machines, manager/state/raft)."""

    def test_host_mesh_shape_and_degradation(self):
        from swarmkit_tpu.parallel import host_row_mesh

        mesh = host_row_mesh(64, hosts=2)
        assert mesh.devices.shape == (2, 4)
        assert mesh.axis_names == ("hosts", "chips")
        # rows=6 can't use 8 devices: chips shrink until hosts*chips | rows
        m2 = host_row_mesh(6, hosts=2)
        assert 6 % (m2.devices.shape[0] * m2.devices.shape[1]) == 0
        # odd rows: HOSTS must shrink too (a 2x1 mesh of 7 rows would be
        # unshardable); 1 host x 7 chips is the valid degradation
        m3 = host_row_mesh(7, hosts=2)
        assert m3.devices.shape == (1, 7)
        # prime rows > device count: worst case collapses to 1x1
        m4 = host_row_mesh(11, hosts=2)
        assert m4.devices.shape == (1, 1)
        # degradation maximizes device USAGE, not host count: rows=10
        # can't use 2x(4,3,2) but CAN use 1x5 — prefer the 5-device mesh
        # (legitimate here: one process, so the partition is simulated)
        m5 = host_row_mesh(10, hosts=2)
        assert m5.devices.shape == (1, 5)

    def test_pick_host_shape_respects_physical_groups(self):
        """On a real multi-process topology the chips axis must not cross
        a host boundary: shapes are bounded by per-host device counts."""
        from swarmkit_tpu.parallel import pick_host_shape

        # 2 hosts x 4 chips, rows=10: a simulated prefix could use 1x5,
        # but 5 chips span hosts — the grouped search picks 2x1 instead
        assert pick_host_shape(10, 2, [4, 4]) == (2, 1)
        # rows=64 uses everything
        assert pick_host_shape(64, 2, [4, 4]) == (2, 4)
        # uneven hosts: chips bounded by the smallest participating host
        assert pick_host_shape(64, 2, [4, 2]) == (2, 2)
        # single host requested on multi-host: stays within host 0
        assert pick_host_shape(64, 1, [4, 2]) == (1, 4)
        # a tiny host must not cap the mesh: with sizes sorted
        # largest-first, 1x4 on the big host beats 2x1 across both
        assert pick_host_shape(4, 2, [4, 1]) == (1, 4)

    def test_2d_mesh_bit_identical_with_faults(self):
        from swarmkit_tpu.parallel import HOST_ROW_AXES, host_row_mesh

        mesh = host_row_mesh(CFG.n, hosts=2)
        kw = dict(prop_count=4, drop_rate=0.1, crash_every=10, down_for=3)
        unsharded, tr_u = run_ticks(init_state(CFG), CFG, 60, **kw)
        sharded_in = shard_rows(init_state(CFG), mesh, axis=HOST_ROW_AXES)
        sharded, tr_s = run_ticks(sharded_in, CFG, 60, **kw)
        assert_states_identical(unsharded, sharded)
        assert (np.asarray(tr_u) == np.asarray(tr_s)).all()

    def test_2d_mesh_sharding_preserved_and_collectives(self):
        from swarmkit_tpu.parallel import HOST_ROW_AXES, host_row_mesh

        mesh = host_row_mesh(CFG.n, hosts=2)
        st = shard_rows(init_state(CFG), mesh, axis=HOST_ROW_AXES)
        out, _ = run_ticks(st, CFG, 4, prop_count=2)
        spec = out.log_term.sharding.spec
        assert spec and tuple(spec[0]) == HOST_ROW_AXES, \
            f"log_term lost its 2-D row sharding: {spec}"
        hlo = jax.jit(step, static_argnames=("cfg",)).lower(
            st, CFG).compile().as_text()
        assert any(op in hlo for op in
                   ("all-to-all", "all-gather", "all-reduce",
                    "collective-permute", "reduce-scatter")), \
            "2-D sharded step lowered without cross-device collectives"

"""Slow wrapper around the bench regression gate (tools/bench_gate.py).

Runs the gate against the repo's real BENCH_r*.json trajectory (must
pass: the newest successful round is also the fastest so far) and
against a copy with a synthetically collapsed final round (must fail).
Slow-marked like the other tool wrappers; tier-1 skips it.
"""

import json
import os
import shutil
import sys

import pytest

REPO_ROOT = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
sys.path.insert(0, os.path.join(REPO_ROOT, "tools"))

from bench_gate import main as gate_main  # noqa: E402
from bench_gate import run_gate  # noqa: E402


def _real_rounds():
    paths = [os.path.join(REPO_ROOT, f) for f in sorted(os.listdir(REPO_ROOT))
             if f.startswith("BENCH_r") and f.endswith(".json")
             and f[len("BENCH_r"):-len(".json")].isdigit()]
    if len(paths) < 2:
        pytest.skip("needs a BENCH_r*.json trajectory in the repo root")
    return paths


@pytest.mark.slow
def test_gate_passes_on_real_trajectory(capsys):
    paths = _real_rounds()
    report = run_gate(paths=paths)
    assert report["ok"], report["failures"]
    # the headline series must actually be gated, not vacuously absent
    assert report["series"]["headline"]["gated"]
    assert gate_main(paths) == 0
    out = capsys.readouterr().out
    assert "PASS" in out and "headline" in out


@pytest.mark.slow
def test_gate_fails_on_injected_regression(tmp_path, capsys):
    paths = _real_rounds()
    copies = []
    for p in paths:
        dst = tmp_path / os.path.basename(p)
        shutil.copy(p, dst)
        copies.append(str(dst))
    # collapse every rate in the newest round far below any tolerance
    last = copies[-1]
    d = json.loads(open(last).read())
    assert d.get("rc") == 0 and isinstance(d.get("parsed"), dict), \
        "newest round must be a usable one for the gate to see the collapse"
    d["parsed"]["value"] *= 0.05
    cfgs = d["parsed"].get("configs_entries_per_s") or {}
    for k, v in cfgs.items():
        if isinstance(v, (int, float)) and not isinstance(v, bool):
            cfgs[k] = v * 0.05
    with open(last, "w") as f:
        json.dump(d, f)

    report = run_gate(paths=copies)
    assert not report["ok"]
    assert any(r.startswith("headline") for r in report["failures"])
    assert gate_main(copies) == 1
    assert "FAIL" in capsys.readouterr().out


def test_gate_ab_ratio_series_extraction(tmp_path):
    # A/B tripwire dicts (densepeer / sparseprog) surface their
    # *_over_dense value as a gated <config>:ratio series; a collapsed
    # lowering ratio fails the gate even when the raw rates hold steady
    paths = []
    for i, ratio in enumerate((1.5, 0.4), start=1):
        p = tmp_path / f"BENCH_r0{i}.json"
        p.write_text(json.dumps({"rc": 0, "parsed": {
            "value": 100.0,
            "configs_entries_per_s": {
                "4096-sparseprog": {
                    "dense": 10.0, "sparse_a16": 10.0 * ratio,
                    "sparse_over_dense": ratio}}}}))
        paths.append(str(p))
    report = run_gate(paths=paths)
    entry = report["series"]["4096-sparseprog:ratio"]
    assert entry["gated"] and entry["last"] == 0.4
    assert not report["ok"]
    assert any(r.startswith("4096-sparseprog:ratio")
               for r in report["failures"])


@pytest.mark.slow
def test_gate_skips_unusable_rounds(tmp_path):
    # rc!=0 and unparsable rounds carry no signal and are skipped whole;
    # with nothing left, the CLI fails loudly instead of passing vacuously
    a = tmp_path / "BENCH_r01.json"
    a.write_text(json.dumps({"rc": 1, "parsed": None}))
    b = tmp_path / "BENCH_r02.json"
    b.write_text("not json")
    report = run_gate(paths=[str(a), str(b)])
    assert report["ok"] and not report["series"]
    assert len(report["skipped_rounds"]) == 2
    assert gate_main([str(a), str(b)]) == 1

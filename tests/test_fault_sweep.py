"""Slow wrapper around the fault-injection sweep (tools/fault_sweep.py).

Runs every fault plan on every wire with the pinned seed and asserts the
differential oracle held (no store divergence, no post-heal liveness
stall) for each scenario.  Excluded from tier-1 by the ``slow`` marker;
run with::

    pytest tests/test_fault_sweep.py -m slow -q
"""

import pytest

from tools.fault_sweep import PLANS, WIRES, run_sweep

PINNED_SEEDS = (2009343,)


@pytest.mark.slow
@pytest.mark.parametrize("wire", WIRES)
def test_fault_sweep_wire(wire):
    results = run_sweep(wires=(wire,), plans=PLANS, seeds=PINNED_SEEDS,
                        verbose=False)
    assert len(results) == len(PLANS) * len(PINNED_SEEDS)
    failed = [r for r in results if not r["ok"]]
    assert not failed, f"fault sweep scenarios failed on {wire}: {failed}"


@pytest.mark.slow
def test_attack_sweep_all_scenarios(tmp_path):
    # the arXiv:2601.00273 attack suite: every scenario must be caught
    # defense-off, shrunk to a replay-exact artifact with the oracle in
    # lockstep, and come back clean defense-on; host wires have no
    # state-injection seam, so each contributes an explicit skip row
    from tools.fault_sweep import ATTACK_SCENARIOS, run_attack_sweep
    results = run_attack_sweep(out_dir=str(tmp_path), verbose=False)
    device = [r for r in results if r["wire"] == "device"]
    skips = [r for r in results if r.get("skipped")]
    assert len(device) == len(ATTACK_SCENARIOS)
    assert len(skips) == len(ATTACK_SCENARIOS) * len(WIRES)
    failed = [r for r in device if not r["ok"]]
    assert not failed, f"attack scenarios failed: {failed}"


@pytest.mark.slow
def test_storage_sweep_all_scenarios(tmp_path):
    # the durability boundary: trip scenarios caught defense-off, shrunk
    # to replay-exact artifacts with a bounded oracle in lockstep, clean
    # with ack-gating on; containment scenarios absorbed with recovery
    # signature evidence; host wires covered by storage.py parity tests
    from tools.fault_sweep import STORAGE_SCENARIOS, run_storage_sweep
    results = run_storage_sweep(out_dir=str(tmp_path), verbose=False)
    device = [r for r in results if r["wire"] == "device"]
    skips = [r for r in results if r.get("skipped")]
    assert len(device) == len(STORAGE_SCENARIOS)
    assert len(skips) == len(STORAGE_SCENARIOS) * len(WIRES)
    failed = [r for r in device if not r["ok"]]
    assert not failed, f"storage scenarios failed: {failed}"

"""Slow wrapper around the fault-injection sweep (tools/fault_sweep.py).

Runs every fault plan on every wire with the pinned seed and asserts the
differential oracle held (no store divergence, no post-heal liveness
stall) for each scenario.  Excluded from tier-1 by the ``slow`` marker;
run with::

    pytest tests/test_fault_sweep.py -m slow -q
"""

import pytest

from tools.fault_sweep import PLANS, WIRES, run_sweep

PINNED_SEEDS = (2009343,)


@pytest.mark.slow
@pytest.mark.parametrize("wire", WIRES)
def test_fault_sweep_wire(wire):
    results = run_sweep(wires=(wire,), plans=PLANS, seeds=PINNED_SEEDS,
                        verbose=False)
    assert len(results) == len(PLANS) * len(PINNED_SEEDS)
    failed = [r for r in results if not r["ok"]]
    assert not failed, f"fault sweep scenarios failed on {wire}: {failed}"

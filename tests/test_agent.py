"""Agent suite: task FSM, worker reconcile, full agent↔dispatcher loop.

Reference scenarios: agent/agent_test.go, agent/worker_test.go,
agent/task_test.go, agent/exec/controller_test.go.
"""

import asyncio
import random

import pytest

from swarmkit_tpu.agent import Agent, AgentConfig, Worker, do_task_state
from swarmkit_tpu.agent.storage import TaskDB
from swarmkit_tpu.agent.testutils import TestExecutor
from swarmkit_tpu.api import (
    Annotations, Node, NodeSpec, NodeState, Secret, SecretSpec, Task,
    TaskSpec, TaskState, TaskStatus,
)
from swarmkit_tpu.api.dispatcher_msgs import (
    Assignment, AssignmentAction, AssignmentChange, AssignmentsMessage,
    AssignmentsType,
)
from swarmkit_tpu.api.objects import NodeStatus
from swarmkit_tpu.manager.dispatcher import Dispatcher
from swarmkit_tpu.store.memory import MemoryStore
from swarmkit_tpu.utils.clock import FakeClock, SystemClock
from tests.conftest import async_test


def make_task(i, state=TaskState.ASSIGNED, desired=TaskState.RUNNING):
    return Task(id=f"task{i}", node_id="node1", spec=TaskSpec(),
                status=TaskStatus(state=state), desired_state=int(desired))


async def eventually(pred, ticks=600, clock=None):
    for _ in range(ticks):
        if pred():
            return
        if clock is not None:
            await asyncio.sleep(0)
            await clock.advance(0.01)
        else:
            # real-clock components (dispatcher debounce) need wall time
            await asyncio.sleep(0.005)
    assert pred(), "condition not met"


# ---------------------------------------------------------------------------
# exec FSM

@async_test
async def test_do_task_state_walks_the_fsm():
    ex = TestExecutor()
    task = make_task(1)
    ctl = await ex.controller(task)
    seen = []
    while True:
        st = await do_task_state(task, ctl, 0.0)
        if st is None or st.state == TaskState.RUNNING:
            if st is not None:
                seen.append(st.state)
            break
        task = task.copy()
        task.status = st
        seen.append(st.state)
    assert seen == [TaskState.ACCEPTED, TaskState.PREPARING, TaskState.READY,
                    TaskState.STARTING, TaskState.RUNNING]


@async_test
async def test_do_task_state_shutdown_short_circuits():
    ex = TestExecutor()
    task = make_task(1, state=TaskState.RUNNING,
                     desired=TaskState.SHUTDOWN)
    ctl = await ex.controller(task)
    st = await do_task_state(task, ctl, 0.0)
    assert st.state == TaskState.SHUTDOWN


@async_test
async def test_do_task_state_failure():
    ex = TestExecutor()
    ex.fail_start = True
    task = make_task(1, state=TaskState.STARTING)
    ctl = await ex.controller(task)
    st = await do_task_state(task, ctl, 0.0)
    assert st.state == TaskState.FAILED
    assert "start failed" in st.err


# ---------------------------------------------------------------------------
# worker

def complete_msg(*tasks, secrets=()):
    changes = [AssignmentChange(assignment=Assignment(task=t))
               for t in tasks]
    changes += [AssignmentChange(assignment=Assignment(secret=s))
                for s in secrets]
    return AssignmentsMessage(type=AssignmentsType.COMPLETE, changes=changes)


@async_test
async def test_worker_runs_assigned_task_to_running():
    ex = TestExecutor()
    w = Worker(ex)
    statuses = []
    w.set_reporter(lambda tid, st: statuses.append((tid, st.state)))
    await w.assign(complete_msg(make_task(1)))
    await eventually(lambda: ("task1", TaskState.RUNNING) in statuses)
    assert w.statuses["task1"].state == TaskState.RUNNING
    await w.close()


@async_test
async def test_worker_complete_set_removes_unassigned():
    ex = TestExecutor()
    w = Worker(ex)
    w.set_reporter(lambda tid, st: None)
    await w.assign(complete_msg(make_task(1), make_task(2)))
    await eventually(lambda: len(w.task_managers) == 2)
    # a new COMPLETE without task2 shuts it down and forgets it
    await w.assign(complete_msg(make_task(1)))
    await eventually(lambda: len(w.task_managers) == 1)
    assert "task1" in w.task_managers
    assert w.db.get_task("task2") is None
    await w.close()


@async_test
async def test_worker_secret_store_follows_assignments():
    ex = TestExecutor()
    w = Worker(ex)
    sec = Secret(id="s1", spec=SecretSpec(annotations=Annotations(name="s1"),
                                          data=b"x"))
    await w.assign(complete_msg(make_task(1), secrets=[sec]))
    assert w.dependencies.secrets.get("s1") is not None
    await w.assign(AssignmentsMessage(
        type=AssignmentsType.INCREMENTAL,
        changes=[AssignmentChange(assignment=Assignment(secret=sec),
                                  action=AssignmentAction.REMOVE)]))
    assert w.dependencies.secrets.get("s1") is None
    await w.close()


@async_test
async def test_worker_resumes_from_db_after_restart():
    db = TaskDB()
    ex = TestExecutor()
    w = Worker(ex, db=db)
    await w.assign(complete_msg(make_task(1)))
    await eventually(lambda: w.statuses.get("task1") is not None
                     and w.statuses["task1"].state == TaskState.RUNNING)
    await w.close()

    # "restart": new worker over the same db resumes the task
    ex2 = TestExecutor()
    w2 = Worker(ex2, db=db)
    await w2.init()
    assert "task1" in w2.task_managers
    # resumed from RUNNING, not from scratch
    assert w2.task_managers["task1"].task.status.state == TaskState.RUNNING
    await w2.close()


# ---------------------------------------------------------------------------
# full agent <-> dispatcher loop

async def agent_setup():
    store = MemoryStore()
    d = Dispatcher(store, rng=random.Random(0))
    await store.update(lambda tx: tx.create(
        Node(id="node1", spec=NodeSpec(annotations=Annotations(name="node1")),
             status=NodeStatus(state=NodeState.UNKNOWN))))
    await d.start(mark_unknown=False)
    ex = TestExecutor()
    agent = Agent(AgentConfig(node_id="node1", executor=ex,
                              connect=lambda: d))
    await agent.start()
    await agent.ready()
    return store, d, ex, agent


@async_test
async def test_agent_end_to_end_task_lifecycle():
    store, d, ex, agent = await agent_setup()
    # node registered READY with the executor's description
    await eventually(lambda: store.get("node", "node1").status.state
                     == NodeState.READY)
    assert store.get("node", "node1").description.hostname == "testhost"

    # a task assigned in the store flows to the agent and comes back RUNNING
    await store.update(lambda tx: tx.create(make_task(1)))
    await eventually(lambda: store.get("task", "task1").status.state
                     == TaskState.RUNNING)

    # desired SHUTDOWN flows down; agent reports SHUTDOWN
    def shut(tx):
        t = tx.get("task", "task1").copy()
        t.desired_state = int(TaskState.SHUTDOWN)
        tx.update(t)
    await store.update(shut)
    await eventually(lambda: store.get("task", "task1").status.state
                     == TaskState.SHUTDOWN)
    await agent.stop()
    await d.stop()


@async_test
async def test_agent_workload_failure_reported():
    store, d, ex, agent = await agent_setup()
    await store.update(lambda tx: tx.create(make_task(1)))
    await eventually(lambda: store.get("task", "task1").status.state
                     == TaskState.RUNNING)
    # the fake workload dies
    ex.controllers["task1"].exit(fail="boom")
    await eventually(lambda: store.get("task", "task1").status.state
                     == TaskState.FAILED)
    assert "boom" in store.get("task", "task1").status.err
    await agent.stop()
    await d.stop()


@async_test
async def test_agent_survives_dispatcher_restart():
    store, d, ex, agent = await agent_setup()
    await store.update(lambda tx: tx.create(make_task(1)))
    await eventually(lambda: store.get("task", "task1").status.state
                     == TaskState.RUNNING)

    # dispatcher restarts (leadership change)
    await d.stop()
    d2 = Dispatcher(store, rng=random.Random(1))
    await d2.start(mark_unknown=True)
    agent.config.connect = lambda: d2

    # agent re-registers and the node comes back READY
    await eventually(lambda: store.get("node", "node1").status.state
                     == NodeState.READY, ticks=2000)
    # the running task is still RUNNING (worker kept it; no restart)
    assert store.get("task", "task1").status.state == TaskState.RUNNING
    await agent.stop()
    await d2.stop()


@async_test
async def test_task_manager_close_reaps_inflight_fsm_step():
    """close() while the FSM step is parked inside controller.wait() must
    cancel the in-flight do_task_state future — a leaked one outlives the
    event loop and asyncio warns 'Task was destroyed but it is pending'
    at teardown (seen in the control-plane soak)."""
    from swarmkit_tpu.agent.task import TaskManager

    class BlockingController:
        async def update(self, task): pass
        async def prepare(self): pass
        async def start(self): pass
        async def wait(self):
            await asyncio.Event().wait()  # blocks forever
        async def shutdown(self): pass
        async def close(self): pass

    statuses = []

    async def report(task_id, status):
        statuses.append(status.state)

    tm = TaskManager(make_task(0), BlockingController(), report,
                     SystemClock())
    tm.start()
    await eventually(lambda: TaskState.RUNNING in statuses)
    # the runner is now blocked in controller.wait() inside do_task_state
    await tm.close()
    await asyncio.sleep(0)
    leaked = [t for t in asyncio.all_tasks()
              if t.get_coro() is not None
              and getattr(t.get_coro(), "__name__", "") == "do_task_state"]
    assert not leaked, f"in-flight FSM step leaked past close: {leaked}"


@async_test
async def test_do_task_state_parks_at_ready_until_promoted():
    """Stop-first updates create replacements at desired READY; the agent
    must not start them until promoted to RUNNING."""
    ex = TestExecutor()
    task = make_task(1, desired=TaskState.READY)
    ctl = await ex.controller(task)
    while True:
        st = await do_task_state(task, ctl, 0.0)
        if st is None:
            break
        task = task.copy()
        task.status = st
    assert task.status.state == TaskState.READY
    # promotion unparks it
    task = task.copy()
    task.desired_state = int(TaskState.RUNNING)
    st = await do_task_state(task, ctl, 0.0)
    assert st.state == TaskState.STARTING


@async_test
async def test_templated_secret_payload_expansion():
    """A secret with the templating driver set has its PAYLOAD expanded
    per task when resolved through the worker's dependency view
    (reference: template/expand.go:132 ExpandSecretSpec,
    template/getter.go:16)."""
    from swarmkit_tpu.agent.testutils import TestExecutor
    from swarmkit_tpu.agent.worker import Worker
    from swarmkit_tpu.api import (
        Annotations, ContainerSpec, Secret, SecretSpec, Task, TaskSpec,
        TaskState,
    )
    from swarmkit_tpu.api.objects import Node as ApiNode
    from swarmkit_tpu.api.specs import Driver, SecretReference
    from swarmkit_tpu.api.types import NodeDescription
    from swarmkit_tpu.utils.clock import FakeClock

    ex = TestExecutor()
    clock = FakeClock()
    w = Worker(ex, clock=clock)
    await w.init()
    node = ApiNode(id="n1", description=NodeDescription(hostname="host-a"))
    w.set_node(node)
    await ex.configure(node)   # the agent session does this in production

    secret = Secret(id="sec1", spec=SecretSpec(
        annotations=Annotations(name="dbcreds"),
        data=b"user={{.Service.Name}}-{{.Task.Slot}}\nhost={{.Node.Hostname}}",
        templating=Driver(name="golang")))
    plain = Secret(id="sec2", spec=SecretSpec(
        annotations=Annotations(name="static"),
        data=b"value={{.Service.Name}}"))   # NO templating: stays literal
    w.dependencies.secrets.add(secret, plain)

    task = Task(id="t1", service_id="s1", slot=4, node_id="n1",
                desired_state=int(TaskState.RUNNING),
                spec=TaskSpec(container=ContainerSpec(
                    image="img",
                    secrets=[SecretReference(secret_id="sec1",
                                             secret_name="dbcreds"),
                             SecretReference(secret_id="sec2",
                                             secret_name="static")])))
    task.service_annotations = Annotations(name="web")
    await w._start_manager(task)
    ctl = ex.controllers["t1"]
    for _ in range(50):
        if getattr(ctl, "resolved_secrets", None):
            break
        await asyncio.sleep(0.01)
    assert ctl.resolved_secrets["dbcreds"] == b"user=web-4\nhost=host-a"
    # un-templated payloads are NEVER expanded
    assert ctl.resolved_secrets["static"] == b"value={{.Service.Name}}"
    # the store's own copy is untouched by per-task expansion
    assert b"{{.Service.Name}}" in w.dependencies.secrets.get("sec1").spec.data
    await w.close()


def test_templated_binary_secret_raises_template_error():
    """A binary (non-UTF-8) payload with templating enabled raises the
    documented TemplateError — not UnicodeDecodeError — so the task FSM
    rejects the task cleanly (advisor round-4 finding)."""
    from swarmkit_tpu.api import Annotations, Secret, SecretSpec, Task
    from swarmkit_tpu.api.specs import Driver
    from swarmkit_tpu.template import TemplateError, expand_secret_spec

    secret = Secret(id="sb", spec=SecretSpec(
        annotations=Annotations(name="binblob"),
        data=b"\xff\xfe\x00binary", templating=Driver(name="golang")))
    task = Task(id="t1", service_id="s1", slot=1, node_id="n1")
    try:
        expand_secret_spec(secret, task)
    except TemplateError as e:
        assert "not valid UTF-8" in str(e)
    else:
        raise AssertionError("expected TemplateError")

"""Unit tests for the typed metrics registry: registration semantics,
label-cardinality bounding, histogram bucket edges, and the Prometheus
text exposition (format 0.0.4) including a golden render.
"""

import pytest

from swarmkit_tpu.metrics import catalog
from swarmkit_tpu.metrics.exposition import render_all, snapshot_all
from swarmkit_tpu.metrics.registry import (
    LabelCardinalityError, MetricError, MetricsRegistry,
    OVERFLOW_LABEL_VALUE,
)


# ---------------------------------------------------------------------------
# registration semantics


def test_get_or_create_returns_same_family():
    r = MetricsRegistry()
    a = r.counter("swarm_x_total", "help.")
    b = r.counter("swarm_x_total", "help.")
    assert a is b


def test_conflicting_schema_raises():
    r = MetricsRegistry()
    r.counter("swarm_x_total", "help.", ("node",))
    with pytest.raises(MetricError):
        r.gauge("swarm_x_total", "help.")
    with pytest.raises(MetricError):
        r.counter("swarm_x_total", "help.", ("peer",))


def test_help_text_mandatory():
    r = MetricsRegistry()
    with pytest.raises(MetricError):
        r.counter("swarm_x_total", "")
    with pytest.raises(MetricError):
        r.counter("swarm_x_total", "   ")


def test_invalid_names_rejected():
    r = MetricsRegistry()
    with pytest.raises(MetricError):
        r.counter("0bad", "help.")
    with pytest.raises(MetricError):
        r.counter("swarm_x_total", "help.", ("bad-label",))
    with pytest.raises(MetricError):
        r.counter("swarm_x_total", "help.", ("__reserved",))


def test_counter_only_goes_up():
    r = MetricsRegistry()
    c = r.counter("swarm_x_total", "help.")
    c.inc(2)
    with pytest.raises(MetricError):
        c.inc(-1)
    assert c.value == 2


def test_labels_schema_enforced():
    r = MetricsRegistry()
    c = r.counter("swarm_x_total", "help.", ("node",))
    with pytest.raises(MetricError):
        c.labels(peer="a")
    with pytest.raises(MetricError):
        c.inc()  # labelled family has no default series
    c.labels(node="a").inc()
    assert c.labels(node="a").value == 1


def test_gauge_set_function_lazily_evaluated_and_fault_tolerant():
    r = MetricsRegistry()
    g = r.gauge("swarm_x", "help.")
    box = [3.0]
    g.set_function(lambda: box[0])
    assert g.value == 3.0
    box[0] = 7.0
    assert g.value == 7.0

    def boom():
        raise RuntimeError("scrape must survive this")

    g2 = r.gauge("swarm_y", "help.")
    g2.set(5.0)
    g2.set_function(boom)
    assert g2.value == 5.0  # last good value, no raise


# ---------------------------------------------------------------------------
# label cardinality


def test_cardinality_overflow_collapses_by_default():
    r = MetricsRegistry()
    c = r.counter("swarm_x_total", "help.", ("id",))
    c.max_label_sets = 4  # direct attr: MAX_LABEL_SETS is the prod default
    for i in range(10):
        c.labels(id=str(i)).inc()
    snap = c.snapshot()
    # 4 real series plus the single overflow series holding the excess
    assert snap[f"id={OVERFLOW_LABEL_VALUE}"] == 6.0
    assert len(snap) == 5


def test_cardinality_overflow_raises_in_strict_mode():
    r = MetricsRegistry(strict=True)
    c = r.counter("swarm_x_total", "help.", ("id",))
    c.max_label_sets = 2
    c.labels(id="a").inc()
    c.labels(id="b").inc()
    with pytest.raises(LabelCardinalityError):
        c.labels(id="c")


# ---------------------------------------------------------------------------
# histogram bucket edges


def test_histogram_bucket_edges_are_upper_inclusive():
    r = MetricsRegistry()
    h = r.histogram("swarm_x_seconds", "help.", buckets=(0.1, 1.0, 10.0))
    h.observe(0.1)    # lands in le=0.1 (upper bound is inclusive)
    h.observe(0.1001)  # lands in le=1.0
    h.observe(10.0)   # lands in le=10.0
    h.observe(99.0)   # overflow -> +Inf only
    child = h.labels()
    assert child.counts == [1, 1, 1, 1]
    assert child.cumulative() == [1, 2, 3, 4]
    assert child.count == 4
    assert child.sum == pytest.approx(0.1 + 0.1001 + 10.0 + 99.0)


def test_histogram_bucket_validation():
    r = MetricsRegistry()
    with pytest.raises(MetricError):
        r.histogram("swarm_x_seconds", "help.", buckets=())
    with pytest.raises(MetricError):
        r.histogram("swarm_y_seconds", "help.", buckets=(1.0, 1.0))


def test_histogram_timer_records_one_observation():
    r = MetricsRegistry()
    h = r.histogram("swarm_x_seconds", "help.", ("call",),
                    buckets=(1.0, 60.0))
    with h.labels(call="a").time():
        pass
    with h.labels(call="a").time():
        pass
    assert h.labels(call="a").count == 2

    plain = r.histogram("swarm_y_seconds", "help.", buckets=(1.0, 60.0))
    with plain.time():
        pass
    assert plain.labels().count == 1


# ---------------------------------------------------------------------------
# exposition


GOLDEN = """\
# HELP swarm_demo_depth Queue depth.
# TYPE swarm_demo_depth gauge
swarm_demo_depth 3
# HELP swarm_demo_seconds Latency.
# TYPE swarm_demo_seconds histogram
swarm_demo_seconds_bucket{le="0.1"} 1
swarm_demo_seconds_bucket{le="1"} 2
swarm_demo_seconds_bucket{le="+Inf"} 3
swarm_demo_seconds_sum 7.6
swarm_demo_seconds_count 3
# HELP swarm_demo_total Things done, by result.
# TYPE swarm_demo_total counter
swarm_demo_total{result="err"} 1
swarm_demo_total{result="ok"} 2
"""


def test_exposition_golden():
    r = MetricsRegistry()
    c = r.counter("swarm_demo_total", "Things done, by result.", ("result",))
    c.labels(result="ok").inc(2)
    c.labels(result="err").inc()
    g = r.gauge("swarm_demo_depth", "Queue depth.")
    g.set(3)
    h = r.histogram("swarm_demo_seconds", "Latency.", buckets=(0.1, 1.0))
    h.observe(0.05)
    h.observe(0.5)
    h.observe(7.05)
    assert r.render() == GOLDEN


def test_exposition_escapes_label_values():
    r = MetricsRegistry()
    c = r.counter("swarm_x_total", "help.", ("path",))
    c.labels(path='a"b\\c\nd').inc()
    line = r.render().splitlines()[-1]
    assert line == 'swarm_x_total{path="a\\"b\\\\c\\nd"} 1'


def test_exposition_escaping_adversarial_label_values():
    """0.0.4 escaping is order-sensitive: backslash FIRST, else the
    backslashes introduced for newline/quote get double-escaped.  These
    values are the classic corruptions (literal \\n in data, trailing
    backslash, quote+newline adjacency)."""
    cases = {
        "\\n": "\\\\n",          # literal backslash-n, NOT a newline
        "a\n": "a\\n",           # real newline becomes the two-char escape
        "q\"\nz": "q\\\"\\nz",   # quote adjacent to newline
        "end\\": "end\\\\",      # trailing backslash cannot eat the quote
        "\\\"": "\\\\\\\"",      # backslash-quote: four + two chars out
    }
    for raw, escaped in cases.items():
        r = MetricsRegistry()
        r.counter("swarm_adv_total", "help.", ("v",)).labels(v=raw).inc()
        line = r.render().splitlines()[-1]
        assert line == f'swarm_adv_total{{v="{escaped}"}} 1', (raw, line)
        # every sample line must stay exactly one exposition line
        assert "\n" not in line


def test_exposition_escapes_help_text():
    """HELP lines escape backslash and newline but keep quotes literal
    (the format treats HELP as raw text to end-of-line)."""
    r = MetricsRegistry()
    r.counter("swarm_h_total", 'multi\nline "quoted" \\path help.')
    rendered = r.render()
    help_line = [ln for ln in rendered.splitlines()
                 if ln.startswith("# HELP")][0]
    assert help_line == ('# HELP swarm_h_total multi\\nline '
                         '"quoted" \\\\path help.')


def test_plain_gauges_escape_help_prefix():
    from swarmkit_tpu.metrics.exposition import render_plain_gauges

    text = render_plain_gauges({"swarm_g": 1.0},
                               help_prefix="evil\nhelp \\x")
    help_line = text.splitlines()[0]
    assert help_line == "# HELP swarm_g evil\\nhelp \\\\x"
    assert text.count("\n") == 3   # HELP + TYPE + sample, newline-terminated


def test_recent_events_section_is_comment_only():
    """Span attrs can contain newlines; the recent-events section must
    stay comment lines so scrapers never parse attr garbage as samples."""
    from swarmkit_tpu.metrics.exposition import render_recent_events
    from swarmkit_tpu.metrics.trace import Tracer

    t = Tracer()
    with t.span("raft.propose", note="line1\nline2 \\ \"q\""):
        pass
    text = render_recent_events(t)
    assert text
    for ln in text.strip().splitlines():
        assert ln.startswith("#"), ln
    assert "\nline2" not in text   # newline arrived escaped, not literal


def test_render_all_merges_three_surfaces():
    from swarmkit_tpu.manager.metrics import Collector
    from swarmkit_tpu.store.memory import MemoryStore
    from swarmkit_tpu.utils.metrics import Registry as LegacyRegistry

    typed = MetricsRegistry()
    typed.counter("swarm_x_total", "help.").inc()
    legacy = LegacyRegistry()
    legacy.timer("swarm_store_read_tx_latency_seconds").observe(0.01)
    collector = Collector(MemoryStore())
    text = render_all(registry=typed, legacy_registry=legacy,
                      collector_gauges=collector.snapshot())
    assert "swarm_x_total 1" in text
    assert "# TYPE swarm_store_read_tx_latency_seconds summary" in text
    assert 'swarm_store_read_tx_latency_seconds{quantile="0.5"}' in text
    assert "swarm_manager_leader 0" in text

    snap = snapshot_all(registry=typed, legacy_registry=legacy,
                        collector_gauges=collector.snapshot())
    assert snap["metrics"]["swarm_x_total"] == 1.0
    assert snap["timers"]["swarm_store_read_tx_latency_seconds"]["count"] == 1
    assert snap["objects"]["swarm_manager_leader"] == 0.0


# ---------------------------------------------------------------------------
# catalog


def test_catalog_instantiates_every_spec_in_strict_registry():
    r = MetricsRegistry(strict=True)
    for name in catalog.CATALOG:
        fam = catalog.get(r, name)
        assert fam.name == name and fam.help


def test_catalog_rejects_unknown_names():
    r = MetricsRegistry()
    with pytest.raises(KeyError):
        catalog.get(r, "swarm_made_up_total")

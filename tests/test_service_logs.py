"""The `service logs` pipeline end to end: executor log capture ->
agent subscription publishers -> LogBroker relay -> client stream,
with Follow/Tail options.

Reference: agent/session.go:249-273 (ListenSubscriptions),
agent/agent.go:207 (subscription handling),
manager/logbroker/broker.go:224-380 (SubscribeLogs/PublishLogs),
api/logbroker.proto:24-28 (SubscribeLogsOptions follow/tail).
"""

from __future__ import annotations

import asyncio

import pytest

from swarmkit_tpu.agent.logs import TaskLogBuffer, selector_matches
from swarmkit_tpu.api import TaskState
from swarmkit_tpu.manager.logbroker import (
    LogSelector, LogStream, SubscribeLogsOptions,
)
from tests.conftest import async_test
from tests.integration_harness import TestCluster


# ---------------------------------------------------------------------------
# unit: the agent-side ring buffer
# ---------------------------------------------------------------------------

def test_task_log_buffer_tail_limits():
    buf = TaskLogBuffer(maxlen=5)
    for i in range(8):
        buf.publish("t1", LogStream.STDOUT, f"line{i}".encode())
    msgs = buf.tail("t1")
    assert [m.data for m in msgs] == [b"line3", b"line4", b"line5",
                                      b"line6", b"line7"]  # ring cap 5
    assert [m.data for m in buf.tail("t1", 2)] == [b"line6", b"line7"]
    assert buf.tail("missing") == []


@async_test
async def test_task_log_buffer_watch():
    buf = TaskLogBuffer()
    w = buf.watch()
    buf.publish("t1", LogStream.STDERR, b"oops", service_id="s1")
    msg = await asyncio.wait_for(w.__anext__(), 2)
    assert msg.data == b"oops" and msg.stream == LogStream.STDERR
    assert msg.context.service_id == "s1"
    w.close()


def test_selector_matches_dimensions():
    class T:
        id = "t1"
        service_id = "s1"

    assert selector_matches(LogSelector(task_ids=["t1"]), T, "n1")
    assert selector_matches(LogSelector(service_ids=["s1"]), T, "n1")
    assert selector_matches(LogSelector(node_ids=["n1"]), T, "n1")
    assert not selector_matches(LogSelector(service_ids=["s2"]), T, "n1")


# ---------------------------------------------------------------------------
# integration: full cluster, tail + follow + multi-node
# ---------------------------------------------------------------------------

async def _cluster_with_service(replicas: int, agents: int = 2):
    c = TestCluster()
    await c.add_manager("m1")
    for i in range(agents):
        await c.add_agent(f"a{i + 1}")
    svc = await c.create_service("logged", replicas=replicas)
    await c.poll(
        lambda: len([t for t in c.running_tasks(svc.id)
                     if t.status.state == TaskState.RUNNING]) == replicas
        or None, "tasks running", timeout=30)
    return c, svc


def _controllers_for(c: TestCluster, svc_id: str):
    out = []
    for node_id, ex in c.executors.items():
        for tid, ctl in ex.controllers.items():
            if ctl.task.service_id == svc_id:
                out.append((node_id, ctl))
    return out


@async_test
async def test_service_logs_follow_across_nodes():
    """Follow mode tails the backlog then streams live lines from every
    node running a matching task."""
    c, svc = await _cluster_with_service(replicas=2, agents=2)
    try:
        lead = c.leader()
        ctls = _controllers_for(c, svc.id)
        assert len(ctls) == 2
        nodes = {node_id for node_id, _ in ctls}
        for node_id, ctl in ctls:
            ctl.write_log(f"backlog-{node_id}")

        got: list = []

        async def consume():
            async for m in lead.logbroker.subscribe_logs(
                    LogSelector(service_ids=[svc.id]),
                    SubscribeLogsOptions(follow=True)):
                got.append(m)

        task = asyncio.get_running_loop().create_task(consume())
        # backlog: the "started" line + our backlog line from BOTH nodes
        await c.poll(lambda: len(got) >= 4 or None, "backlog", timeout=15)
        datas = {m.data for m in got}
        for node_id in nodes:
            assert f"backlog-{node_id}".encode() in datas

        # live lines keep flowing in follow mode
        for node_id, ctl in ctls:
            ctl.write_log(f"live-{node_id}")
        await c.poll(lambda: len(got) >= 6 or None, "live lines",
                     timeout=15)
        datas = {m.data for m in got}
        for node_id in nodes:
            assert f"live-{node_id}".encode() in datas
        # context identifies the task and node
        assert {m.context.node_id for m in got} == nodes
        task.cancel()
    finally:
        await c.stop_all()


@async_test
async def test_service_logs_no_follow_completes_with_tail():
    """follow=False returns the backlog (tail-limited) and the stream
    ENDS once every matching node published its close marker."""
    c, svc = await _cluster_with_service(replicas=1, agents=1)
    try:
        lead = c.leader()
        (node_id, ctl), = _controllers_for(c, svc.id)
        for i in range(6):
            ctl.write_log(f"l{i}")

        got = []
        async for m in lead.logbroker.subscribe_logs(
                LogSelector(service_ids=[svc.id]),
                SubscribeLogsOptions(follow=False, tail=3)):
            got.append(m)
        # the iterator ENDED on its own (non-follow completion) with the
        # last 3 buffered lines
        assert [m.data for m in got] == [b"l3", b"l4", b"l5"]
    finally:
        await c.stop_all()


@async_test
async def test_service_logs_task_selector_and_late_task():
    """A task-id selector only gets that task's lines; a subscription
    re-announce picks up tasks scheduled after the subscribe."""
    c, svc = await _cluster_with_service(replicas=1, agents=2)
    try:
        lead = c.leader()
        (node_id, ctl), = _controllers_for(c, svc.id)
        ctl.write_log("mine")

        got = []

        async def consume():
            async for m in lead.logbroker.subscribe_logs(
                    LogSelector(task_ids=[ctl.task.id]),
                    SubscribeLogsOptions(follow=True)):
                got.append(m)

        task = asyncio.get_running_loop().create_task(consume())
        await c.poll(lambda: any(m.data == b"mine" for m in got) or None,
                     "task line", timeout=15)
        assert all(m.context.task_id == ctl.task.id for m in got)

        # scale up: the new task's lines reach a service-selector
        # subscription opened BEFORE the task existed
        got2 = []

        async def consume2():
            async for m in lead.logbroker.subscribe_logs(
                    LogSelector(service_ids=[svc.id]),
                    SubscribeLogsOptions(follow=True, tail=0)):
                got2.append(m)

        task2 = asyncio.get_running_loop().create_task(consume2())
        await asyncio.sleep(0.2)
        cur = lead.control_api.get_service(svc.id)
        spec = cur.spec.copy()
        spec.replicated.replicas = 2
        await lead.control_api.update_service(svc.id, spec,
                                              version=cur.meta.version.index)
        await c.poll(
            lambda: len([t for t in c.running_tasks(svc.id)
                         if t.status.state == TaskState.RUNNING]) == 2
            or None, "scaled", timeout=30)
        ctls = _controllers_for(c, svc.id)
        new = [x for x in ctls if x[1].task.id != ctl.task.id]
        assert new
        new[0][1].write_log("from-the-new-task")
        # two more lines land right behind: the tail snapshot the
        # publisher ships for the just-discovered task may include them,
        # and the live bus delivers them too — the seq dedup must keep
        # exactly one copy of each (advisor round-4 finding)
        new[0][1].write_log("burst-2")
        new[0][1].write_log("burst-3")
        await c.poll(lambda: sum(1 for m in got2
                                 if m.data == b"burst-3") >= 1 or None,
                     "late task lines", timeout=15)
        await asyncio.sleep(0.3)   # give any duplicate time to show up
        seen = [(m.context.task_id, m.data) for m in got2]
        assert len(seen) == len(set(seen)), \
            f"duplicate log lines delivered: {seen}"
        task.cancel()
        task2.cancel()
    finally:
        await c.stop_all()


@async_test
async def test_no_follow_timeout_is_truncation_error_not_clean_eof():
    """If max_wait expires while nodes still owe their backlog, the
    subscription FAILS with LogsTruncated — a silent eof would be
    indistinguishable from a complete tail (advisor round-4 finding;
    the 'truncation must be a failure' rule ctl._stream_logs enforces)."""
    import pytest

    from swarmkit_tpu.manager.logbroker import (
        LogBroker, LogSelector, LogsTruncated, SubscribeLogsOptions,
    )
    from swarmkit_tpu.store.memory import MemoryStore

    lb = LogBroker(MemoryStore())
    with pytest.raises(LogsTruncated, match="never published"):
        async for _ in lb.subscribe_logs(
                LogSelector(node_ids=["ghost-node"]),
                SubscribeLogsOptions(follow=False, tail=-1, max_wait=0.05)):
            pass

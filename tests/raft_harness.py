"""Deterministic in-memory multi-node raft harness for tests.

Mirrors the role of manager/state/raft/testutils (real nodes, fake clock) in
the reference: real RawNode state machines, an explicit message bus instead of
gRPC, and ticks pumped by the test.
"""

from __future__ import annotations

import random
from typing import Callable, Optional

from swarmkit_tpu.raft import (
    Config, ConfChange, ConfChangeType, Entry, EntryType, MsgType, RawNode,
)


class InMemCluster:
    def __init__(self, ids, election_tick=10, heartbeat_tick=1,
                 check_quorum=False, pre_vote=False, seed=0,
                 max_size_per_msg=64):
        self.ids = list(ids)
        self.nodes: dict[int, RawNode] = {}
        self.applied: dict[int, list[bytes]] = {i: [] for i in ids}
        # log index of each item in self.applied (parallel lists), so restart
        # can trim re-appliable entries by index rather than list position.
        self.applied_idx: dict[int, list[int]] = {i: [] for i in ids}
        self.down: set[int] = set()
        self.partitions: set[tuple[int, int]] = set()  # directed (frm, to)
        self.drop_fn: Optional[Callable[[object], bool]] = None
        self.rng = random.Random(seed)
        self.cfg = dict(election_tick=election_tick,
                        heartbeat_tick=heartbeat_tick,
                        check_quorum=check_quorum, pre_vote=pre_vote,
                        max_size_per_msg=max_size_per_msg)
        for i in ids:
            self.nodes[i] = RawNode(
                Config(id=i, peers=tuple(ids), seed=seed, **self.cfg))

    # -- topology control --------------------------------------------------
    def stop(self, pid: int) -> None:
        self.down.add(pid)

    def start(self, pid: int) -> None:
        self.down.discard(pid)

    def restart(self, pid: int, wipe: bool = False) -> None:
        """Recreate the node from its 'persisted' state (log survives unless
        wiped), modeling a process restart."""
        old = self.nodes[pid]
        if wipe:
            node = RawNode(Config(id=pid, peers=tuple(self.ids),
                                  seed=self.rng.randrange(1 << 30), **self.cfg))
            self.applied[pid] = []
            self.applied_idx[pid] = []
        else:
            log = old.raft.log
            log.pending_snapshot = None
            # Committed-but-compacted entries stay applied; everything above
            # the snapshot boundary re-applies from the log after restart.
            log.applied = log.offset
            keep = [k for k, i in enumerate(self.applied_idx[pid])
                    if i <= log.offset]
            self.applied[pid] = [self.applied[pid][k] for k in keep]
            self.applied_idx[pid] = [self.applied_idx[pid][k] for k in keep]
            node = RawNode(
                Config(id=pid, peers=(), seed=self.rng.randrange(1 << 30),
                       **self.cfg),
                log=log, hard_state=old.raft.hard_state(),
                voters=old.raft.voter_ids())
        self.nodes[pid] = node
        self.down.discard(pid)

    def partition(self, *groups) -> None:
        """Only nodes within the same group can talk."""
        self.partitions = set()
        group_of = {}
        for gi, g in enumerate(groups):
            for pid in g:
                group_of[pid] = gi
        for a in self.ids:
            for b in self.ids:
                if a != b and group_of.get(a) != group_of.get(b):
                    self.partitions.add((a, b))

    def heal(self) -> None:
        self.partitions = set()

    # -- pumping -----------------------------------------------------------
    def _deliverable(self, m) -> bool:
        if m.to in self.down or m.frm in self.down:
            return False
        if (m.frm, m.to) in self.partitions:
            return False
        if self.drop_fn is not None and self.drop_fn(m):
            return False
        return True

    def flush(self, max_rounds: int = 100) -> None:
        """Drain Readys and deliver messages until quiescent."""
        for _ in range(max_rounds):
            moved = False
            for pid in self.ids:
                if pid in self.down:
                    continue
                node = self.nodes[pid]
                if not node.has_ready():
                    continue
                rd = node.ready()
                moved = moved or rd.contains_updates()
                for e in rd.committed_entries:
                    self._apply(pid, e)
                node.advance(rd)
                for m in rd.messages:
                    if m.to in self.nodes and self._deliverable(m):
                        self.nodes[m.to].step(m)
            if not moved:
                return

    def _apply(self, pid: int, e: Entry) -> None:
        if e.type == EntryType.CONF_CHANGE:
            from swarmkit_tpu.raft.wire import decode_conf_change
            cc: ConfChange = decode_conf_change(e.data)
            self.nodes[pid].apply_conf_change(cc)
            if cc.type == ConfChangeType.ADD_NODE and cc.node_id not in self.nodes:
                # Instantiate the new member (empty log; will catch up).
                self.ids.append(cc.node_id)
                self.applied[cc.node_id] = []
                self.applied_idx[cc.node_id] = []
                self.nodes[cc.node_id] = RawNode(
                    Config(id=cc.node_id, peers=(),
                           seed=self.rng.randrange(1 << 30), **self.cfg),
                    voters=(cc.node_id,))
                # Joiner learns membership out of band (reference: JoinResponse
                # carries the member list).
                for v in self.nodes[pid].raft.voter_ids():
                    self.nodes[cc.node_id].raft.add_node(v)
        elif e.data:
            self.applied[pid].append(e.data)
            self.applied_idx[pid].append(e.index)

    def tick(self, pid: Optional[int] = None) -> None:
        targets = [pid] if pid is not None else self.ids
        for t in targets:
            if t not in self.down:
                self.nodes[t].tick()
        self.flush()

    def ticks(self, n: int, pid: Optional[int] = None) -> None:
        for _ in range(n):
            self.tick(pid)

    # -- queries -----------------------------------------------------------
    def leader(self) -> Optional[int]:
        leaders = {p for p in self.ids
                   if p not in self.down
                   and self.nodes[p].raft.state == "leader"}
        if not leaders:
            return None
        # With partitions there may transiently be two; report highest term.
        return max(leaders, key=lambda p: self.nodes[p].raft.term)

    def elect(self, pid: int) -> None:
        self.nodes[pid].campaign()
        self.flush()
        assert self.nodes[pid].raft.state == "leader", self.status()

    def wait_leader(self, max_ticks: int = 200) -> int:
        for _ in range(max_ticks):
            lead = self.leader()
            if lead is not None:
                return lead
            self.tick()
        raise AssertionError(f"no leader after {max_ticks} ticks: {self.status()}")

    def propose(self, data: bytes, pid: Optional[int] = None) -> None:
        target = pid if pid is not None else self.leader()
        assert target is not None, "no leader to propose to"
        self.nodes[target].propose(data)
        self.flush()

    def committed(self, pid: int) -> int:
        return self.nodes[pid].raft.log.committed

    def status(self) -> dict:
        return {p: self.nodes[p].status() for p in self.ids}

    def up_ids(self):
        return [p for p in self.ids if p not in self.down]

"""In-process raft-node cluster harness for tests.

Behavioral reference: manager/state/raft/testutils/testutils.go — real nodes,
real (in-process) transport, FAKE clock pumped explicitly: AdvanceTicks
(:52), WaitForCluster (:61), NewInitNode/NewJoinNode, Restart/ShutdownNode.
"""

from __future__ import annotations

import asyncio
import os
import tempfile
from typing import Optional

from swarmkit_tpu.raft.node import Node, NodeOpts
from swarmkit_tpu.raft.transport import Network
from swarmkit_tpu.utils.clock import FakeClock

TICK = 1.0  # one raft tick per simulated second


class RaftHarness:
    """Builds clusters of swarmkit_tpu.raft.node.Node with a shared fake
    clock and in-process network."""

    def __init__(self, seed: int = 7) -> None:
        self.clock = FakeClock()
        self.network = Network(seed=seed)
        self.nodes: dict[str, Node] = {}
        self.tmp = tempfile.TemporaryDirectory(prefix="swarmkit-raft-")
        self._n = 0
        self.seed = seed

    def _opts(self, node_id: str, join_addr: str = "",
              force_new_cluster: bool = False, **kw) -> NodeOpts:
        return NodeOpts(
            node_id=node_id,
            addr=f"{node_id}.test:4242",
            network=self.network,
            state_dir=os.path.join(self.tmp.name, node_id),
            clock=self.clock,
            join_addr=join_addr,
            force_new_cluster=force_new_cluster,
            tick_interval=TICK,
            election_tick=4,      # testutils uses small timeouts too
            heartbeat_tick=1,
            seed=self.seed + self._n,
            **kw,
        )

    async def add_node(self, join_from: Optional[Node] = None, **kw) -> Node:
        self._n += 1
        node_id = f"node-{self._n}"
        join_addr = join_from.addr if join_from is not None else ""
        node = Node(self._opts(node_id, join_addr=join_addr, **kw))
        self.nodes[node_id] = node
        await node.start()
        await self.pump()
        return node

    async def restart_node(self, node: Node, force_new_cluster: bool = False,
                           **kw) -> Node:
        """Start a fresh Node object over the same state dir
        (reference: testutils.Restart)."""
        self._n += 0
        opts = self._opts(node.node_id, force_new_cluster=force_new_cluster,
                          **kw)
        opts.seed = node.opts.seed
        new = Node(opts)
        self.nodes[node.node_id] = new
        await new.start()
        await self.pump()
        return new

    async def shutdown_node(self, node: Node) -> None:
        await node.stop()
        self.network.unregister(node.addr)

    async def pump(self, n: int = 1) -> None:
        """Yield so queued transport deliveries and run loops progress."""
        for _ in range(max(1, n) * 8):
            await asyncio.sleep(0)

    async def tick(self, ticks: int = 1) -> None:
        """reference: AdvanceTicks testutils.go:52."""
        for _ in range(ticks):
            await self.clock.advance(TICK)
            await self.pump()

    def leader(self) -> Optional[Node]:
        leaders = [n for n in self.nodes.values()
                   if n.running and n.is_leader()]
        return leaders[0] if leaders else None

    async def wait_for_leader(self, max_ticks: int = 100) -> Node:
        for _ in range(max_ticks):
            lead = self.leader()
            if lead is not None:
                return lead
            await self.tick()
        raise TimeoutError("no leader elected")

    async def wait_for_cluster(self, max_ticks: int = 200) -> Node:
        """Converged: one leader, same term, all running members applied up
        to the leader's commit (reference: WaitForCluster testutils.go:61)."""
        for _ in range(max_ticks):
            lead = self.leader()
            if lead is not None:
                members = [n for n in self.nodes.values() if n.running]
                lt = lead._raw.raft.term
                lc = lead._raw.raft.log.committed
                if all(n._raw is not None
                       and n._raw.raft.term == lt
                       and n._raw.raft.log.applied >= lc
                       for n in members):
                    return lead
            await self.tick()
        raise TimeoutError("cluster did not converge")

    async def wait_for(self, pred, max_ticks: int = 200) -> None:
        for _ in range(max_ticks):
            if pred():
                return
            await self.tick()
        raise TimeoutError("condition not met")

    async def close(self) -> None:
        for n in list(self.nodes.values()):
            if n.running:
                await n.stop()
        self.tmp.cleanup()

"""Raft Node suite against the device-mesh Transport (BASELINE acceptance
gate: the raft scenarios run with messages exchanged through sharded device
mailbox arrays instead of the in-process wire).

Reference bar: the same scenarios as tests/test_raft_node.py
(manager/state/raft/raft_test.go:63-1025), with the Transport seam
(transport/transport.go:26) bound to swarmkit_tpu.transport
.DeviceMeshTransport over the 8-virtual-device CPU mesh (tests/conftest.py).
"""

import pytest

from swarmkit_tpu.api import Annotations, Node as ApiNode, NodeSpec
from swarmkit_tpu.raft.node import ErrLostLeadership
from swarmkit_tpu.transport import DeviceMeshNet, DeviceMeshTransport
from tests.conftest import async_test
from tests.node_harness import RaftHarness


class DeviceRaftHarness(RaftHarness):
    """RaftHarness with the device-mesh wire + transport selected."""

    def __init__(self, seed: int = 7) -> None:
        super().__init__(seed=seed)
        self.network = DeviceMeshNet(seed=seed, rows=8)

    def _opts(self, node_id, **kw):
        opts = super()._opts(node_id, **kw)
        opts.transport_factory = DeviceMeshTransport
        return opts

    async def close(self) -> None:
        await super().close()
        self.network.close()


def _obj(i):
    return ApiNode(id=f"id{i}",
                   spec=NodeSpec(annotations=Annotations(name=f"obj{i}")))


async def propose(node, i):
    await node.store.update(lambda tx: tx.create(_obj(i)))


def has_obj(node, i):
    return node.store.get("node", f"id{i}") is not None


@async_test
async def test_three_node_bootstrap_and_replication():
    h = DeviceRaftHarness()
    try:
        n1 = await h.add_node()
        await h.wait_for_leader()
        n2 = await h.add_node(join_from=n1)
        n3 = await h.add_node(join_from=n1)
        await h.wait_for_cluster()
        assert len(n1.cluster.members) == 3
        await propose(n1, 1)
        await h.wait_for(lambda: has_obj(n2, 1) and has_obj(n3, 1))
        # messages really moved through the device exchange
        assert h.network.device_flushes > 0
        assert h.network.device_messages > 0
    finally:
        await h.close()


@async_test
async def test_leader_down_reelection_and_continued_replication():
    h = DeviceRaftHarness()
    try:
        n1 = await h.add_node()
        await h.wait_for_leader()
        n2 = await h.add_node(join_from=n1)
        n3 = await h.add_node(join_from=n1)
        await h.wait_for_cluster()
        await h.shutdown_node(n1)
        lead = await h.wait_for_leader()
        assert lead in (n2, n3)
        await propose(lead, 5)
        others = [n for n in (n2, n3) if n is not lead]
        await h.wait_for(lambda: all(has_obj(n, 5) for n in others))
    finally:
        await h.close()


@async_test
async def test_five_node_replication_and_quorum():
    """5-node scenario: replication to all; quorum loss blocks commits;
    healing recovers (raft_test.go TestRaftQuorumFailure/Recovery)."""
    import asyncio

    h = DeviceRaftHarness()
    try:
        n1 = await h.add_node()
        await h.wait_for_leader()
        rest = [await h.add_node(join_from=n1) for _ in range(4)]
        await h.wait_for_cluster()
        nodes = [n1, *rest]
        await propose(n1, 1)
        await h.wait_for(lambda: all(has_obj(n, 1) for n in nodes))

        # cut the leader + one follower off from the other three
        lead = h.leader()
        others = [n for n in nodes if n is not lead]
        h.network.partition({lead.addr, others[0].addr},
                            {n.addr for n in others[1:]})
        task = asyncio.ensure_future(propose(lead, 2))
        for _ in range(40):
            if task.done():
                break
            await h.tick()
        assert task.done(), "proposal neither committed nor timed out"
        with pytest.raises((TimeoutError, ErrLostLeadership)):
            task.result()

        h.network.heal()
        lead = await h.wait_for_cluster()
        await propose(lead, 3)
        await h.wait_for(lambda: all(has_obj(n, 3) for n in nodes
                                     if n.running))
    finally:
        await h.close()


@async_test
async def test_snapshot_catch_up_through_device_mailbox():
    """Snapshot messages (the largest payloads) survive the mailbox
    word-packing round trip (raft_test.go TestRaftSnapshot)."""
    h = DeviceRaftHarness()
    try:
        n1 = await h.add_node(snapshot_interval=10,
                              log_entries_for_slow_followers=2)
        await h.wait_for_leader()
        for i in range(15):
            await propose(n1, i)
        assert n1.status()["snapshot_index"] > 0
        n2 = await h.add_node(join_from=n1)
        await h.wait_for(lambda: all(has_obj(n2, i) for i in range(15)))
        assert len(n2.cluster.members) == 2
    finally:
        await h.close()


@async_test
async def test_message_drop_still_converges_on_device_wire():
    """20% per-edge loss applied ON DEVICE as mailbox masks; raft retries
    mask it (BASELINE churn analog)."""
    h = DeviceRaftHarness()
    try:
        n1 = await h.add_node()
        await h.wait_for_leader()
        n2 = await h.add_node(join_from=n1)
        n3 = await h.add_node(join_from=n1)
        await h.wait_for_cluster()
        for a in (n1, n2, n3):
            for b in (n1, n2, n3):
                if a is not b:
                    h.network.set_drop(a.addr, b.addr, 0.2)
        lead = h.leader()
        await propose(lead, 1)
        await h.wait_for(lambda: all(has_obj(n, 1) for n in (n1, n2, n3)))
    finally:
        await h.close()


@async_test
async def test_member_removal_on_device_wire():
    h = DeviceRaftHarness()
    try:
        n1 = await h.add_node()
        await h.wait_for_leader()
        n2 = await h.add_node(join_from=n1)
        n3 = await h.add_node(join_from=n1)
        await h.wait_for_cluster()
        removed_id = n3.raft_id
        await n1.remove_member(removed_id)
        await h.wait_for(lambda: len(n1.cluster.members) == 2)
        assert n1.cluster.is_id_removed(removed_id)
        await propose(n1, 4)
        await h.wait_for(lambda: has_obj(n2, 4))
    finally:
        await h.close()


def test_exchange_lowers_to_cross_device_collective():
    """The delivery program's sender->receiver resharding must be a real
    cross-device collective over the mesh, not 8 replicas (VERDICT r02
    weak #6)."""
    import numpy as np

    net = DeviceMeshNet(rows=8)
    assert len(net.mesh.devices.ravel()) == 8, "conftest provides 8 devices"
    fn = net._exchange_fn(4, 64)
    words = np.zeros((8, 8, 4, 64), np.uint32)
    lens = np.zeros((8, 8, 4), np.int32)
    keep = np.zeros((8, 8, 4), bool)
    hlo = fn.lower(words, lens, keep).compile().as_text()
    assert ("all-to-all" in hlo or "collective-permute" in hlo
            or "all-gather" in hlo), \
        f"no cross-device collective in exchange HLO:\n{hlo[:2000]}"

"""Scheduler tests (reference: manager/scheduler/scheduler_test.go)."""

import asyncio

import pytest

from swarmkit_tpu.api import (
    Annotations, Node, NodeAvailability, NodeDescription, NodeSpec, NodeState,
    Resources, ResourceRequirements, Task, TaskSpec, TaskState, TaskStatus,
    Placement,
)
from swarmkit_tpu.api.objects import NodeStatus
from swarmkit_tpu.api.types import NodeResources, Platform
from swarmkit_tpu.manager.scheduler import Scheduler
from swarmkit_tpu.store.memory import MemoryStore
from swarmkit_tpu.utils.clock import FakeClock
from tests.conftest import async_test


def make_node(i, cpus=4_000_000_000, mem=8 << 30, labels=None, os="linux"):
    return Node(
        id=f"node{i}",
        spec=NodeSpec(annotations=Annotations(name=f"node{i}",
                                              labels=labels or {}),
                      availability=NodeAvailability.ACTIVE),
        description=NodeDescription(
            hostname=f"host{i}",
            platform=Platform(architecture="x86_64", os=os),
            resources=NodeResources(nano_cpus=cpus, memory_bytes=mem)),
        status=NodeStatus(state=NodeState.READY),
    )


def make_task(i, service="svc", cpus=0, mem=0, constraints=None, prefs=None):
    spec = TaskSpec()
    if cpus or mem:
        spec.resources = ResourceRequirements(
            reservations=Resources(nano_cpus=cpus, memory_bytes=mem))
    if constraints or prefs:
        spec.placement = Placement(constraints=constraints or [],
                                   preferences=prefs or [])
    return Task(id=f"task{i}", service_id=service, slot=i, spec=spec,
                status=TaskStatus(state=TaskState.PENDING),
                desired_state=int(TaskState.RUNNING))


async def pump(clock, seconds=1.0, steps=8):
    for _ in range(steps):
        await asyncio.sleep(0)
    await clock.advance(seconds)
    for _ in range(steps):
        await asyncio.sleep(0)


@async_test
async def test_basic_assignment_spreads_least_loaded():
    clock = FakeClock()
    store = MemoryStore(clock=clock.now)
    sched = Scheduler(store, clock=clock)
    await store.update(lambda tx: [tx.create(make_node(i))
                                   for i in range(3)])
    await sched.start()
    await store.update(lambda tx: [tx.create(make_task(i))
                                   for i in range(6)])
    await pump(clock)
    await pump(clock)
    tasks = store.find("task")
    assert all(t.status.state == TaskState.ASSIGNED for t in tasks), \
        [(t.id, t.status.state) for t in tasks]
    per_node = {}
    for t in tasks:
        per_node[t.node_id] = per_node.get(t.node_id, 0) + 1
    assert all(c == 2 for c in per_node.values()), per_node
    await sched.stop()


@async_test
async def test_failure_taint_steers_placement_and_spec_change_escapes():
    """A node that keeps failing a service's tasks loses placement ties
    (reference countRecentFailures backoff), but the taint is keyed by the
    VERSIONED service — failures of the broken old spec must not penalize
    the operator's fixed new spec (reference nodeinfo.go versionedService)."""
    clock = FakeClock()
    store = MemoryStore(clock=clock.now)
    sched = Scheduler(store, clock=clock)
    await store.update(lambda tx: [tx.create(make_node(0)),
                                   tx.create(make_node(1))])
    await sched.start()
    await pump(clock)

    # 5 tasks fail on node0 under spec A -> node0 is tainted for svc@A
    failed = []
    for i in range(5):
        t = make_task(100 + i)
        t.node_id = "node0"
        t.status.state = TaskState.ASSIGNED
        await store.update(lambda tx, t=t: tx.create(t))
        await pump(clock, seconds=0.1)

        def fail(tx, tid=t.id):
            cur = tx.get("task", tid)
            cur.status.state = TaskState.FAILED
            cur.desired_state = int(TaskState.SHUTDOWN)
            tx.update(cur)
        await store.update(fail)
        failed.append(t)
        await pump(clock, seconds=0.1)

    # new tasks of the SAME spec all avoid the tainted node0
    await store.update(lambda tx: [tx.create(make_task(i))
                                   for i in range(4)])
    await pump(clock)
    await pump(clock)
    same = [store.get("task", f"task{i}") for i in range(4)]
    assert all(t.status.state == TaskState.ASSIGNED for t in same)
    assert all(t.node_id == "node1" for t in same), \
        [(t.id, t.node_id) for t in same]

    # a CHANGED spec escapes the taint: spreading resumes across BOTH nodes
    changed = []
    for i in range(10, 14):
        t = make_task(i, cpus=1_000_000)   # different spec fingerprint
        changed.append(t)
    await store.update(lambda tx: [tx.create(t) for t in changed])
    await pump(clock)
    await pump(clock)
    nodes_used = {store.get("task", t.id).node_id for t in changed}
    assert "node0" in nodes_used, \
        "fixed spec still penalized by the old spec's failures"
    await sched.stop()


@async_test
async def test_resource_filter_blocks_oversubscription():
    clock = FakeClock()
    store = MemoryStore(clock=clock.now)
    sched = Scheduler(store, clock=clock)
    # one tiny node: 1 cpu
    await store.update(lambda tx: tx.create(make_node(1, cpus=1_000_000_000)))
    await sched.start()
    # two tasks each wanting the full cpu: only one fits
    await store.update(lambda tx: [
        tx.create(make_task(1, cpus=1_000_000_000)),
        tx.create(make_task(2, cpus=1_000_000_000))])
    await pump(clock)
    await pump(clock)
    tasks = store.find("task")
    assigned = [t for t in tasks if t.status.state == TaskState.ASSIGNED]
    pending = [t for t in tasks if t.status.state == TaskState.PENDING]
    assert len(assigned) == 1 and len(pending) == 1
    # free the node: delete the assigned task -> pending one gets scheduled
    await store.update(lambda tx: tx.delete("task", assigned[0].id))
    await pump(clock)
    await pump(clock)
    t2 = store.get("task", pending[0].id)
    assert t2.status.state == TaskState.ASSIGNED
    await sched.stop()


@async_test
async def test_constraint_filter():
    clock = FakeClock()
    store = MemoryStore(clock=clock.now)
    sched = Scheduler(store, clock=clock)
    await store.update(lambda tx: [
        tx.create(make_node(1, labels={"zone": "a"})),
        tx.create(make_node(2, labels={"zone": "b"}))])
    await sched.start()
    await store.update(lambda tx: [
        tx.create(make_task(1, constraints=["node.labels.zone==b"]))])
    await pump(clock)
    t = store.get("task", "task1")
    assert t.status.state == TaskState.ASSIGNED and t.node_id == "node2"
    await sched.stop()


@async_test
async def test_unready_and_drained_nodes_excluded():
    clock = FakeClock()
    store = MemoryStore(clock=clock.now)
    sched = Scheduler(store, clock=clock)
    down = make_node(1)
    down.status.state = NodeState.DOWN
    drained = make_node(2)
    drained.spec.availability = NodeAvailability.DRAIN
    ok = make_node(3)
    await store.update(lambda tx: [tx.create(down), tx.create(drained),
                                   tx.create(ok)])
    await sched.start()
    await store.update(lambda tx: [tx.create(make_task(i))
                                   for i in range(4)])
    await pump(clock)
    tasks = store.find("task")
    assert all(t.node_id == "node3" for t in tasks
               if t.status.state == TaskState.ASSIGNED)
    assert sum(1 for t in tasks
               if t.status.state == TaskState.ASSIGNED) == 4
    await sched.stop()


@async_test
async def test_spread_preference_over_zones():
    clock = FakeClock()
    store = MemoryStore(clock=clock.now)
    sched = Scheduler(store, clock=clock)
    # 2 zones, 2 nodes each
    await store.update(lambda tx: [
        tx.create(make_node(1, labels={"zone": "a"})),
        tx.create(make_node(2, labels={"zone": "a"})),
        tx.create(make_node(3, labels={"zone": "b"})),
        tx.create(make_node(4, labels={"zone": "b"}))])
    await sched.start()
    await store.update(lambda tx: [
        tx.create(make_task(i, prefs=["spread=node.labels.zone"]))
        for i in range(4)])
    await pump(clock)
    await pump(clock)
    tasks = store.find("task")
    zones = {"a": 0, "b": 0}
    for t in tasks:
        assert t.status.state == TaskState.ASSIGNED
        zones["a" if t.node_id in ("node1", "node2") else "b"] += 1
    assert zones == {"a": 2, "b": 2}, zones
    await sched.stop()


@async_test
async def test_node_removal_frees_nothing_but_new_node_triggers_tick():
    clock = FakeClock()
    store = MemoryStore(clock=clock.now)
    sched = Scheduler(store, clock=clock)
    await sched.start()
    # no nodes: task stays pending
    await store.update(lambda tx: tx.create(make_task(1)))
    await pump(clock)
    assert store.get("task", "task1").status.state == TaskState.PENDING
    # add a node: pending task gets scheduled
    await store.update(lambda tx: tx.create(make_node(1)))
    await pump(clock)
    await pump(clock)
    assert store.get("task", "task1").status.state == TaskState.ASSIGNED
    await sched.stop()


def test_plugin_filter_network_and_log_drivers():
    """PluginFilter (reference filter.go:104-201): a task attached to a
    driver-named network only lands on nodes whose engine reports the
    Network/<driver> plugin; named log drivers filter only when the node
    reports Log/ plugins at all."""
    from swarmkit_tpu.api.specs import Driver
    from swarmkit_tpu.api.types import NetworkAttachment
    from swarmkit_tpu.manager.scheduler.filters import PluginFilter
    from swarmkit_tpu.manager.scheduler.nodeinfo import NodeInfo

    def info(plugins, with_desc=True):
        n = make_node(1)
        if with_desc:
            n.description.engine.plugins = list(plugins)
        else:
            n.description = None
        return NodeInfo(node=n)

    f = PluginFilter()
    t = make_task("svc1")
    # no plugin references: filter disabled
    assert f.set_task(t) is False

    t.networks = [NetworkAttachment(network_id="n1", driver="overlay")]
    assert f.set_task(t) is True
    assert f.check(info(["Network/overlay"])) is True
    assert f.check(info(["Network/bridge"])) is False
    assert f.check(info([])) is False
    assert f.check(info([], with_desc=False)) is True  # no engine: pass

    t2 = make_task("svc2")
    t2.spec.log_driver = Driver(name="fluentd")
    assert f.set_task(t2) is True
    # node reports no Log/ plugins at all: lenient pass (older engine)
    assert f.check(info(["Network/overlay"])) is True
    assert f.check(info(["Log/json-file"])) is False
    assert f.check(info(["Log/fluentd"])) is True


def test_plugin_filter_uses_resolved_cluster_default_log_driver():
    """new_task resolves ClusterSpec.task_defaults.log_driver onto
    task.log_driver; the PluginFilter reads the RESOLVED field so
    cluster-default drivers are filtered too (reference: newTask task.go +
    filter.go t.LogDriver)."""
    from swarmkit_tpu.api import Cluster, ClusterSpec, Service, ServiceSpec
    from swarmkit_tpu.api.specs import Driver, TaskDefaults
    from swarmkit_tpu.manager.orchestrator import common
    from swarmkit_tpu.manager.scheduler.filters import PluginFilter
    from swarmkit_tpu.manager.scheduler.nodeinfo import NodeInfo

    cluster = Cluster(id="c1", spec=ClusterSpec(
        task_defaults=TaskDefaults(log_driver=Driver(name="fluentd"))))
    svc = Service(id="s1", spec=ServiceSpec(task=TaskSpec()))
    t = common.new_task(cluster, svc, slot=1)
    assert t.log_driver is not None and t.log_driver.name == "fluentd"

    f = PluginFilter()
    assert f.set_task(t) is True
    n = make_node(1)
    n.description.engine.plugins = ["Log/json-file"]
    assert f.check(NodeInfo(node=n)) is False
    n.description.engine.plugins = ["Log/fluentd"]
    assert f.check(NodeInfo(node=n)) is True


@async_test
async def test_preassigned_pending_tasks_confirmed_to_assigned():
    """Global-service tasks arrive PENDING with the node already pinned;
    the scheduler validates the fit and flips them to ASSIGNED — and a
    task pinned to a node that fails the pipeline stays pending until the
    node changes (reference: pendingPreassignedTasks +
    processPreassignedTasks scheduler.go)."""
    clock = FakeClock()
    store = MemoryStore(clock=clock.now)
    sched = Scheduler(store, clock=clock)
    good = make_node(1)
    tiny = make_node(2, cpus=1_000_000, mem=1 << 20)   # too small
    await store.update(lambda tx: [tx.create(good), tx.create(tiny)])
    await sched.start()

    t_ok = make_task(1)
    t_ok.node_id = "node1"
    t_ok.status.state = TaskState.PENDING
    t_big = make_task(2, cpus=2_000_000_000, mem=1 << 30)
    t_big.node_id = "node2"
    t_big.status.state = TaskState.PENDING
    await store.update(lambda tx: [tx.create(t_ok), tx.create(t_big)])
    await pump(clock)

    assert store.get("task", t_ok.id).status.state == TaskState.ASSIGNED
    assert store.get("task", t_ok.id).node_id == "node1"
    # pinned node lacks resources: stays PENDING (retried on node change)
    assert store.get("task", t_big.id).status.state == TaskState.PENDING

    # the pinned node grows -> the pending preassigned task is confirmed
    n2 = store.get("node", "node2")
    n2.description.resources.nano_cpus = 8_000_000_000
    n2.description.resources.memory_bytes = 8 << 30
    await store.update(lambda tx: tx.update(n2))
    await pump(clock)
    assert store.get("task", t_big.id).status.state == TaskState.ASSIGNED
    await sched.stop()


@async_test
async def test_preassigned_task_does_not_compete_with_its_own_reservation():
    """The event mirror books a pinned PENDING task's reservation onto its
    node; the fit check must exclude it or a task reserving more than half
    the node's resources deadlocks itself PENDING forever (reference:
    processPreassignedTasks removes the task from nodeInfo first)."""
    clock = FakeClock()
    store = MemoryStore(clock=clock.now)
    sched = Scheduler(store, clock=clock)
    node = make_node(1, cpus=3_000_000_000, mem=4 << 30)
    await store.update(lambda tx: tx.create(node))
    await sched.start()
    # reserves 2/3 of the node: with the self-competition bug, available
    # shows 1e9 < 2e9 and the task never leaves PENDING
    t = make_task(1, cpus=2_000_000_000, mem=1 << 30)
    t.node_id = "node1"
    t.status.state = TaskState.PENDING
    await store.update(lambda tx: tx.create(t))
    await pump(clock)
    assert store.get("task", t.id).status.state == TaskState.ASSIGNED
    await sched.stop()

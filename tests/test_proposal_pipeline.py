"""Batched proposal pipeline (store/pipeline.py): coalescing semantics,
FIFO composition, conflict detection, failure unwinding, and the
leader-killed-mid-batch crash invariants.

Reference framing: the reference serializes every write through one
ProposeValue round (manager/state/raft); the pipeline keeps that
linearization while packing concurrent transactions into one raft entry.
The invariants pinned here: commit-callback-only application (no entry
applies twice), FIFO apply order within and across packed proposals,
stale external reads still fail ErrSequenceConflict, and a mid-batch
leadership loss never loses an acknowledged write.
"""

import asyncio

from swarmkit_tpu.api import Annotations, Config, ConfigSpec
from swarmkit_tpu.store import ErrSequenceConflict, MemoryStore, NopProposer
from swarmkit_tpu.store.pipeline import CoalesceConfig, ProposalPipeline
from tests.conftest import async_test


def _cfg(i, data=b"x"):
    return Config(id=f"cfg{i}",
                  spec=ConfigSpec(annotations=Annotations(name=f"cfg{i}"),
                                  data=data))


def _store(window=0.0, max_entries=256) -> tuple[MemoryStore, NopProposer]:
    from swarmkit_tpu.metrics.registry import MetricsRegistry

    p = NopProposer()
    s = MemoryStore(proposer=p, obs=MetricsRegistry())
    s.set_coalescing(CoalesceConfig(window=window, max_entries=max_entries))
    return s, p


@async_test
async def test_concurrent_updates_pack_into_one_proposal():
    s, p = _store()
    await asyncio.gather(*(
        s.update(lambda tx, i=i: tx.create(_cfg(i))) for i in range(64)))
    # all txns applied, far fewer raft rounds than txns
    assert len(s.find("config")) == 64
    assert len(p.proposed) < 64
    assert sum(len(actions) for actions in p.proposed) == 64
    # every txn packed into one proposal commits at that proposal's raft
    # index, so versions are non-decreasing in FIFO order with one
    # distinct index per proposal
    versions = [s.get("config", f"cfg{i}").meta.version.index
                for i in range(64)]
    assert versions == sorted(versions)
    assert len(set(versions)) == len(p.proposed)


@async_test
async def test_fifo_read_modify_write_composition():
    """Later writers queued in the same window must observe earlier
    pending writes (speculative overlay), composing like a serial
    history."""
    s, _ = _store()
    await s.update(lambda tx: tx.create(_cfg(0, data=b"a")))

    def appender(tx):
        c = tx.get("config", "cfg0")
        c.spec.data = c.spec.data + b"y"
        tx.update(c)

    await asyncio.gather(*(s.update(appender) for _ in range(8)))
    assert s.get("config", "cfg0").spec.data == b"a" + b"y" * 8


@async_test
async def test_stale_external_read_still_conflicts():
    """A writer holding a pre-batch snapshot must fail the version check
    against provisional in-queue versions (lost-update prevention)."""
    s, _ = _store()
    await s.update(lambda tx: tx.create(_cfg(0, data=b"a")))
    stale = s.get("config", "cfg0")

    async def bump():
        def m(tx):
            c = tx.get("config", "cfg0")
            c.spec.data = b"b"
            tx.update(c)
        await s.update(m)

    async def stale_write():
        def m(tx):
            stale.spec.data = b"lost"
            tx.update(stale)
        await s.update(m)

    results = await asyncio.gather(bump(), stale_write(),
                                   return_exceptions=True)
    assert any(isinstance(r, ErrSequenceConflict) for r in results)
    assert s.get("config", "cfg0").spec.data == b"b"


@async_test
async def test_batch_block_routes_through_pipeline():
    """store.batch() with more changes than one txn allows splits into
    packed chunks and applies every change exactly once."""
    s, p = _store()
    batch = s.batch()
    for i in range(500):
        await batch.update(lambda tx, i=i: tx.create(_cfg(i)))
    applied = await batch.commit()
    assert applied == 500
    assert len(s.find("config")) == 500
    assert sum(len(a) for a in p.proposed) >= 500
    assert len(p.proposed) < 500


@async_test
async def test_max_entries_chunking():
    s, p = _store(max_entries=8)
    await asyncio.gather(*(
        s.update(lambda tx, i=i: tx.create(_cfg(i))) for i in range(32)))
    assert len(s.find("config")) == 32
    assert all(len(actions) <= 8 for actions in p.proposed)


class _FailingProposer(NopProposer):
    """Fails the first `fail_n` proposals before committing (the
    ErrLostLeadership shape: the future errors, nothing applies)."""

    def __init__(self, fail_n: int, exc: Exception) -> None:
        super().__init__()
        self.fail_n = fail_n
        self.exc = exc

    async def propose_value(self, actions, apply_cb):
        if self.fail_n > 0:
            self.fail_n -= 1
            raise self.exc
        await super().propose_value(actions, apply_cb)


@async_test
async def test_proposal_failure_unwinds_all_pending():
    """A failed proposal fails every queued writer (their reads may have
    observed the dirty overlay) and leaves the store consistent for the
    next epoch."""
    boom = RuntimeError("lost leadership")
    p = _FailingProposer(1, boom)
    s = MemoryStore(proposer=p)
    s.set_coalescing(CoalesceConfig(window=0.0))
    results = await asyncio.gather(*(
        s.update(lambda tx, i=i: tx.create(_cfg(i))) for i in range(16)),
        return_exceptions=True)
    assert all(isinstance(r, RuntimeError) for r in results)
    assert s.find("config") == []
    # the next epoch is clean: fresh writes pack and commit
    await asyncio.gather(*(
        s.update(lambda tx, i=i: tx.create(_cfg(i))) for i in range(16)))
    assert len(s.find("config")) == 16


@async_test
async def test_stop_coalescing_drains_and_falls_back():
    s, p = _store()
    await asyncio.gather(*(
        s.update(lambda tx, i=i: tx.create(_cfg(i))) for i in range(8)))
    await s.stop_coalescing()
    assert not s.coalescing()
    await s.update(lambda tx: tx.create(_cfg(99)))
    assert len(s.find("config")) == 9
    # the post-stop write went through the sequential path: one action
    assert len(p.proposed[-1]) == 1


@async_test
async def test_leader_killed_mid_batch_no_lost_no_double_applied():
    """Crash safety: fire concurrent writes through the coalescing leader
    and kill it mid-flight.  Acknowledged writes must survive on the new
    leader (no lost); every id exists at most once with a single version
    (no double-apply); unacknowledged writes may have landed or not (the
    reference's ambiguous-failure semantic) but the survivors agree."""
    import tempfile

    from swarmkit_tpu.manager.manager import Manager
    from swarmkit_tpu.raft.transport import Network

    net = Network(seed=5)
    tmp = tempfile.TemporaryDirectory(prefix="pipeline-crash-")
    mgrs = []
    try:
        for i in range(3):
            m = Manager(node_id=f"m{i}", addr=f"m{i}:4242", network=net,
                        state_dir=f"{tmp.name}/m{i}",
                        join_addr=mgrs[0].addr if mgrs else "",
                        tick_interval=0.05, election_tick=4, seed=i,
                        coalesce=CoalesceConfig(window=0.001))
            await m.start()
            mgrs.append(m)
            if i == 0:
                while not m.is_leader():
                    await asyncio.sleep(0.02)
        lead = mgrs[0]

        outcomes: dict[int, BaseException | None] = {}

        async def one(i):
            try:
                await lead.store.update(
                    lambda tx, i=i: tx.create(_cfg(i)))
                outcomes[i] = None
            except BaseException as e:
                outcomes[i] = e

        writers = [asyncio.create_task(one(i)) for i in range(32)]
        # let some proposals commit, then partition the leader away —
        # an abrupt failure, NOT the graceful stop path (stop() drains
        # the pipeline).  A second wave lands on the now-isolated
        # leader: it cannot reach quorum, CheckQuorum steps it down,
        # and the pipeline must fail every queued writer.
        while len(outcomes) < 8:
            await asyncio.sleep(0.001)
        net.partition([lead.addr], [mgrs[1].addr, mgrs[2].addr])
        writers += [asyncio.create_task(one(i)) for i in range(32, 64)]
        await asyncio.wait_for(asyncio.gather(*writers), timeout=30)

        new_lead = None
        for _ in range(400):
            new_lead = next((m for m in mgrs[1:] if m.is_leader()), None)
            if new_lead is not None:
                break
            await asyncio.sleep(0.05)
        assert new_lead is not None, "no new leader elected"
        net.heal()

        present = {c.id for c in new_lead.store.find("config")}
        acked = {i for i, e in outcomes.items() if e is None}
        failed = {i for i, e in outcomes.items() if e is not None}
        assert acked, "test never observed a committed write"
        assert failed, "leader kill raced past every in-flight write"
        # no lost acknowledged write
        missing = {i for i in acked if f"cfg{i}" not in present}
        assert not missing, f"acked writes lost after failover: {missing}"
        # no double-apply / divergence: both majority members converged
        # to the same config set at the same versions (a re-applied
        # packed entry would skew versions between replicas)
        follower = mgrs[1] if new_lead is mgrs[2] else mgrs[2]
        for _ in range(200):
            f_present = {c.id for c in follower.store.find("config")}
            if f_present == present:
                break
            await asyncio.sleep(0.05)
        assert {c.id for c in follower.store.find("config")} == present
        for cid in present:
            assert (follower.store.get("config", cid).meta.version.index
                    == new_lead.store.get("config", cid).meta.version.index)
        # failed writers can retry on the new leader: create succeeds iff
        # the original never landed, else the id is already present
        from swarmkit_tpu.store import ErrExist
        for i in failed:
            try:
                await new_lead.store.update(
                    lambda tx, i=i: tx.create(_cfg(i)))
            except ErrExist:
                pass
        present2 = {c.id for c in new_lead.store.find("config")}
        assert present2 == {f"cfg{i}" for i in range(64)}
    finally:
        for m in mgrs:
            try:
                await m.stop()
            except Exception:
                pass


@async_test
async def test_pipeline_metric_names_cover_module():
    """The module's METRIC_NAMES/SAMPLE_LABELS stay in sync with what the
    pipeline actually emits (metrics_lint check #12 locks the catalog
    side)."""
    from swarmkit_tpu.store import pipeline as mod

    assert set(mod.METRIC_NAMES) == {
        "swarm_cpl_proposals_total", "swarm_cpl_txns_total",
        "swarm_cpl_batch_entries", "swarm_cpl_queue_depth"}
    for labels in mod.METRIC_NAMES.values():
        for lbl in labels:
            assert lbl in mod.SAMPLE_LABELS


@async_test
async def test_pipeline_counts_outcomes():
    s, _ = _store()
    from swarmkit_tpu.metrics import catalog as obs_catalog
    await asyncio.gather(*(
        s.update(lambda tx, i=i: tx.create(_cfg(i))) for i in range(16)))
    packed = obs_catalog.get(s.obs, "swarm_cpl_proposals_total") \
        .labels(outcome="committed").value
    txns = obs_catalog.get(s.obs, "swarm_cpl_txns_total") \
        .labels(outcome="committed").value
    assert txns == 16 and 1 <= packed <= 16

"""Control API tests (reference: manager/controlapi/*_test.go)."""

import asyncio

import pytest

from swarmkit_tpu.api import (
    Annotations, Cluster, ClusterSpec, ConfigSpec, ContainerSpec,
    GlobalService, Mode, NetworkSpec, Node, NodeAvailability, NodeRole,
    NodeSpec, NodeState, ReplicatedService, SecretSpec, ServiceSpec,
    TaskSpec, TaskState,
)
from swarmkit_tpu.api.objects import NodeStatus
from swarmkit_tpu.api.specs import SecretReference
from swarmkit_tpu.api.types import EndpointSpecRef, PortConfig
from swarmkit_tpu.manager.controlapi import (
    AlreadyExists, ControlApi, FailedPrecondition, InvalidArgument, NotFound,
)
from swarmkit_tpu.store.memory import MemoryStore
from tests.conftest import async_test, requires_cryptography


def api():
    return ControlApi(MemoryStore())


def service_spec(name="web", image="nginx", replicas=2, **kw):
    return ServiceSpec(
        annotations=Annotations(name=name),
        task=TaskSpec(container=ContainerSpec(image=image)),
        replicated=ReplicatedService(replicas=replicas), **kw)


@async_test
async def test_create_service_validation():
    c = api()
    with pytest.raises(InvalidArgument):   # no name
        await c.create_service(ServiceSpec(
            task=TaskSpec(container=ContainerSpec(image="x"))))
    with pytest.raises(InvalidArgument):   # bad name
        await c.create_service(service_spec(name="-bad-"))
    with pytest.raises(InvalidArgument):   # no image
        await c.create_service(service_spec(image=""))
    with pytest.raises(InvalidArgument):   # no container
        await c.create_service(ServiceSpec(
            annotations=Annotations(name="x"), task=TaskSpec()))
    with pytest.raises(InvalidArgument):   # bad constraint
        spec = service_spec()
        from swarmkit_tpu.api import Placement
        spec.task.placement = Placement(constraints=["node.id === x"])
        await c.create_service(spec)
    with pytest.raises(InvalidArgument):   # duplicate published port
        await c.create_service(service_spec(endpoint=EndpointSpecRef(ports=[
            PortConfig(protocol="tcp", target_port=80, published_port=8080),
            PortConfig(protocol="tcp", target_port=81, published_port=8080),
        ])))

    # mount validation (reference service.go validateMounts)
    from swarmkit_tpu.api.specs import Mount

    def with_mounts(*mounts):
        s = service_spec()
        s.task.container.mounts = list(mounts)
        return s
    with pytest.raises(InvalidArgument):   # no target
        await c.create_service(with_mounts(Mount(type="bind", source="/x")))
    with pytest.raises(InvalidArgument):   # duplicate target
        await c.create_service(with_mounts(
            Mount(type="volume", source="v1", target="/d"),
            Mount(type="volume", source="v2", target="/d")))
    with pytest.raises(InvalidArgument):   # bind without source
        await c.create_service(with_mounts(Mount(type="bind", target="/d")))
    with pytest.raises(InvalidArgument):   # tmpfs with source
        await c.create_service(with_mounts(
            Mount(type="tmpfs", source="/x", target="/d")))
    with pytest.raises(InvalidArgument):   # unknown type
        await c.create_service(with_mounts(
            Mount(type="fuse", source="/x", target="/d")))

    # negative resource quantities would INFLATE scheduler availability
    from swarmkit_tpu.api import ResourceRequirements, Resources
    with pytest.raises(InvalidArgument):
        s = service_spec()
        s.task.resources = ResourceRequirements(
            reservations=Resources(generic={"tpu-chip": -4}))
        await c.create_service(s)
    with pytest.raises(InvalidArgument):
        s = service_spec()
        s.task.resources = ResourceRequirements(
            limits=Resources(nano_cpus=-1))
        await c.create_service(s)

    svc = await c.create_service(service_spec())
    assert c.get_service(svc.id).spec.annotations.name == "web"
    with pytest.raises(AlreadyExists):     # duplicate name
        await c.create_service(service_spec())


@async_test
async def test_create_service_unknown_secret_rejected():
    c = api()
    spec = service_spec()
    spec.task.container.secrets = [SecretReference(secret_id="nope")]
    with pytest.raises(InvalidArgument):
        await c.create_service(spec)


@async_test
async def test_update_service_version_and_mode_gates():
    c = api()
    svc = await c.create_service(service_spec())
    cur = c.get_service(svc.id)

    spec2 = service_spec(replicas=5)
    updated = await c.update_service(svc.id, spec2,
                                     version=cur.meta.version.index)
    assert updated.spec.replicated.replicas == 5
    assert updated.previous_spec.replicated.replicas == 2

    # stale version rejected
    with pytest.raises(FailedPrecondition):
        await c.update_service(svc.id, service_spec(replicas=7),
                               version=cur.meta.version.index)
    # mode change rejected
    gspec = ServiceSpec(annotations=Annotations(name="web"),
                        task=TaskSpec(container=ContainerSpec(image="x")),
                        mode=Mode.GLOBAL, global_=GlobalService())
    with pytest.raises(InvalidArgument):
        await c.update_service(svc.id, gspec)
    # rename rejected
    with pytest.raises(InvalidArgument):
        await c.update_service(svc.id, service_spec(name="web2"))


@async_test
async def test_remove_service():
    c = api()
    svc = await c.create_service(service_spec())
    await c.remove_service(svc.id)
    with pytest.raises(NotFound):
        c.get_service(svc.id)
    with pytest.raises(NotFound):
        await c.remove_service(svc.id)


@async_test
async def test_node_remove_gates():
    c = api()
    store = c.store
    mk = lambda i, role, state: Node(
        id=f"n{i}", spec=NodeSpec(annotations=Annotations(name=f"n{i}"),
                                  desired_role=role),
        role=role, status=NodeStatus(state=state))
    await store.update(lambda tx: [
        tx.create(mk(1, NodeRole.MANAGER, NodeState.READY)),
        tx.create(mk(2, NodeRole.WORKER, NodeState.READY)),
        tx.create(mk(3, NodeRole.WORKER, NodeState.DOWN)),
    ])
    with pytest.raises(FailedPrecondition):   # manager can't be removed
        await c.remove_node("n1")
    with pytest.raises(FailedPrecondition):   # ready worker needs force
        await c.remove_node("n2")
    await c.remove_node("n2", force=True)
    await c.remove_node("n3")                 # down worker is fine
    assert [n.id for n in c.list_nodes()] == ["n1"]


@async_test
async def test_demote_last_manager_rejected():
    c = api()
    n = Node(id="n1", spec=NodeSpec(annotations=Annotations(name="n1"),
                                    desired_role=NodeRole.MANAGER),
             role=NodeRole.MANAGER, status=NodeStatus(state=NodeState.READY))
    await c.store.update(lambda tx: tx.create(n))
    spec = n.spec.copy()
    spec.desired_role = NodeRole.WORKER
    with pytest.raises(FailedPrecondition):
        await c.update_node("n1", spec)


@async_test
async def test_network_remove_in_use_rejected():
    c = api()
    net = await c.create_network(NetworkSpec(
        annotations=Annotations(name="overlay1")))
    svc = await c.create_service(service_spec(networks=[net.id]))
    with pytest.raises(FailedPrecondition):
        await c.remove_network(net.id)
    await c.remove_service(svc.id)
    await c.remove_network(net.id)
    with pytest.raises(NotFound):
        c.get_network(net.id)


@async_test
async def test_secret_lifecycle_and_redaction():
    c = api()
    with pytest.raises(InvalidArgument):   # empty data
        await c.create_secret(SecretSpec(annotations=Annotations(name="s")))
    with pytest.raises(InvalidArgument):   # too big
        await c.create_secret(SecretSpec(
            annotations=Annotations(name="s"), data=b"x" * (501 * 1024)))
    sec = await c.create_secret(SecretSpec(
        annotations=Annotations(name="s"), data=b"payload"))
    # reads redact the payload; the store keeps it
    assert c.get_secret(sec.id).spec.data == b""
    assert c.list_secrets()[0].spec.data == b""
    assert c.store.get("secret", sec.id).spec.data == b"payload"

    # only label updates allowed
    with pytest.raises(InvalidArgument):
        await c.update_secret(sec.id, SecretSpec(
            annotations=Annotations(name="s"), data=b"other"))
    upd = await c.update_secret(sec.id, SecretSpec(
        annotations=Annotations(name="s", labels={"env": "prod"})))
    assert upd.spec.annotations.labels == {"env": "prod"}

    # in-use secrets cannot be removed
    spec = service_spec()
    spec.task.container.secrets = [SecretReference(secret_id=sec.id,
                                                   secret_name="s")]
    svc = await c.create_service(spec)
    with pytest.raises(FailedPrecondition):
        await c.remove_secret(sec.id)
    await c.remove_service(svc.id)
    await c.remove_secret(sec.id)


@requires_cryptography
@async_test
async def test_cluster_update_and_token_rotation():
    from swarmkit_tpu.ca import RootCA

    c = api()
    cl = Cluster(id="c1", spec=ClusterSpec(
        annotations=Annotations(name="default")))
    cl.root_ca.ca_cert = RootCA.create().cert_pem
    cl.root_ca.join_token_worker = "SWMTKN-1-old-worker"
    cl.root_ca.join_token_manager = "SWMTKN-1-old-manager"
    await c.store.update(lambda tx: tx.create(cl))

    got = c.get_cluster()
    assert got.id == "c1"
    spec = got.spec.copy()
    spec.raft.snapshot_interval = 5000
    updated = await c.update_cluster("c1", spec,
                                     version=got.meta.version.index,
                                     rotate_worker_token=True)
    assert updated.spec.raft.snapshot_interval == 5000
    assert updated.root_ca.join_token_worker != "SWMTKN-1-old-worker"
    assert updated.root_ca.join_token_worker.startswith("SWMTKN-1-")
    assert updated.root_ca.join_token_manager == "SWMTKN-1-old-manager"


@async_test
async def test_extension_resource_lifecycle():
    c = api()
    ext = await c.create_extension(Annotations(name="widgets"))
    res = await c.create_resource(Annotations(name="w1"), "widgets",
                                  payload=b"{}")
    with pytest.raises(InvalidArgument):   # unknown kind
        await c.create_resource(Annotations(name="w2"), "nope")
    with pytest.raises(FailedPrecondition):  # in use
        await c.remove_extension(ext.id)
    await c.remove_resource(res.id)
    await c.remove_extension(ext.id)


@async_test
async def test_list_filters():
    c = api()
    await c.create_service(service_spec(name="web-a"))
    await c.create_service(service_spec(name="web-b"))
    await c.create_service(service_spec(name="api"))
    assert len(c.list_services()) == 3
    assert len(c.list_services(name_prefixes=["web-"])) == 2
    assert [s.spec.annotations.name
            for s in c.list_services(names=["api"])] == ["api"]

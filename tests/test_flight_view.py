"""Slow wrapper around the flight-record viewer (tools/flight_view.py).

Generates real records — one from a recorded kernel run, one from the
seed-pinned DST mutation post-mortem — then drives the CLI end to end:
summarize, export (schema-checked Chrome trace), and diff.  Excluded
from tier-1 by the ``slow`` marker; run with::

    pytest tests/test_flight_view.py -m slow -q
"""

import dataclasses
import json

import pytest

from swarmkit_tpu.flightrec import record as flight_record
from swarmkit_tpu.raft.sim.run import run_ticks
from swarmkit_tpu.raft.sim.state import SimConfig, init_state
from tools.flight_view import main as flight_view_main


def _cfg(seed):
    return SimConfig(n=5, log_len=64, window=8, apply_batch=16, max_props=8,
                     keep=4, election_tick=10, seed=seed,
                     record_events=True, event_ring=128)


def _make_record(path, seed, ticks=60):
    cfg = _cfg(seed)
    final, _ = run_ticks(init_state(cfg), cfg, ticks, prop_count=1)
    rec = flight_record.capture(final, trigger="manual",
                                meta={"seed": seed, "ticks": ticks})
    flight_record.save_record(rec, str(path))
    return rec


@pytest.mark.slow
def test_flight_view_end_to_end(tmp_path, capsys):
    a = tmp_path / "a.json"
    b = tmp_path / "b.json"
    rec = _make_record(a, seed=3)
    _make_record(b, seed=4)

    # summarize
    assert flight_view_main(["summarize", str(a), "--last", "5"]) == 0
    out = capsys.readouterr().out
    assert f"{len(rec.events)} events" in out
    assert "COMMIT_ADVANCE" in out

    # export --check: schema-valid Chrome trace lands on disk
    trace_path = tmp_path / "a.trace.json"
    assert flight_view_main(["export", str(a), "-o", str(trace_path),
                             "--check"]) == 0
    trace = json.loads(trace_path.read_text())
    assert isinstance(trace["traceEvents"], list) and trace["traceEvents"]
    phases = {t["ph"] for t in trace["traceEvents"]}
    assert "i" in phases and "M" in phases

    # diff: different seeds diverge (exit 1), self-diff is clean (exit 0)
    assert flight_view_main(["diff", str(a), str(b)]) == 1
    out = capsys.readouterr().out
    assert "first divergence" in out
    assert flight_view_main(["diff", str(a), str(a)]) == 0


@pytest.mark.slow
def test_flight_view_on_dst_postmortem_record(tmp_path, capsys):
    """The DST violation post-mortem record flows through the same CLI:
    capture_flight -> save -> summarize/export."""
    from swarmkit_tpu import dst

    cfg = dataclasses.replace(_cfg(0), record_events=False)
    sched, names = dst.make_batch(cfg, schedules=24, ticks=100, seed=0)
    res = dst.explore(init_state(cfg), cfg, sched, names, prop_count=2,
                      mutation="commit_no_quorum", shard=False)
    assert len(res.violating) > 0
    s = int(res.violating[0])
    cap = dst.capture_flight(cfg, sched.slice(s), 2, "commit_no_quorum",
                             first_tick=int(res.first_tick[s]))
    path = tmp_path / "postmortem.json"
    flight_record.save_record(cap["record"], str(path))

    assert flight_view_main(["summarize", str(path)]) == 0
    out = capsys.readouterr().out
    assert "trigger=dst_violation" in out
    assert "leader_completeness" in out   # meta carries the violation

    assert flight_view_main(["export", str(path), "-o",
                             str(tmp_path / "pm.trace.json"),
                             "--check"]) == 0

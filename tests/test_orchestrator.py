"""Orchestrator suite: replicated/global reconciliation, restart policy,
rolling updates, task reaper, constraint enforcer.

Reference scenarios: manager/orchestrator/replicated/*_test.go,
restart/restart_test.go, update/updater_test.go, global/global_test.go,
taskreaper/task_reaper_test.go, constraintenforcer/constraint_enforcer_test.go.
"""

import asyncio

from swarmkit_tpu.api import (
    Annotations, Mode, Node, NodeAvailability, NodeDescription, NodeSpec,
    NodeState, Placement, ReplicatedService, RestartCondition, RestartPolicy,
    Service, ServiceSpec, TaskSpec, TaskState, UpdateConfig, ContainerSpec,
    GlobalService,
)
from swarmkit_tpu.api.objects import NodeStatus
from swarmkit_tpu.manager.orchestrator import common
from swarmkit_tpu.manager.orchestrator.constraintenforcer import ConstraintEnforcer
from swarmkit_tpu.manager.orchestrator.global_ import GlobalOrchestrator
from swarmkit_tpu.manager.orchestrator.replicated import ReplicatedOrchestrator
from swarmkit_tpu.manager.orchestrator.restart import RestartSupervisor
from swarmkit_tpu.manager.orchestrator.taskreaper import TaskReaper
from swarmkit_tpu.store.by import ByService
from swarmkit_tpu.store.memory import MemoryStore
from swarmkit_tpu.utils.clock import FakeClock
from tests.conftest import async_test


def make_service(name="web", replicas=3, image="nginx:1", mode=Mode.REPLICATED,
                 restart=None, update=None, constraints=None):
    spec = ServiceSpec(
        annotations=Annotations(name=name),
        task=TaskSpec(container=ContainerSpec(image=image), restart=restart,
                      placement=Placement(constraints=constraints or [])),
        mode=mode,
        update=update,
    )
    if mode == Mode.REPLICATED:
        spec.replicated = ReplicatedService(replicas=replicas)
    else:
        spec.global_ = GlobalService()
    return Service(id=f"svc-{name}", spec=spec)


def make_node(i):
    return Node(id=f"node{i}",
                spec=NodeSpec(annotations=Annotations(name=f"node{i}")),
                description=NodeDescription(hostname=f"host{i}"),
                status=NodeStatus(state=NodeState.READY))


async def pump(clock, seconds=0.0, steps=12):
    for _ in range(steps):
        await asyncio.sleep(0)
    if seconds:
        await clock.advance(seconds)
        for _ in range(steps):
            await asyncio.sleep(0)


def live_tasks(store, sid):
    return [t for t in store.find("task", ByService(sid))
            if t.desired_state <= TaskState.RUNNING
            and not common.in_terminal_state(t)]


@async_test
async def test_replicated_scale_up_and_down():
    clock = FakeClock()
    store = MemoryStore(clock=clock.now)
    orch = ReplicatedOrchestrator(store, clock=clock)
    await orch.start()
    svc = make_service(replicas=3)
    await store.update(lambda tx: tx.create(svc))
    await pump(clock)
    tasks = live_tasks(store, svc.id)
    assert len(tasks) == 3
    assert sorted(t.slot for t in tasks) == [1, 2, 3]

    # scale up
    svc2 = store.get("service", svc.id)
    svc2.spec.replicated.replicas = 5
    await store.update(lambda tx: tx.update(svc2))
    await pump(clock)
    assert len(live_tasks(store, svc.id)) == 5

    # scale down
    svc3 = store.get("service", svc.id)
    svc3.spec.replicated.replicas = 2
    await store.update(lambda tx: tx.update(svc3))
    await pump(clock)
    assert len(live_tasks(store, svc.id)) == 2
    await orch.stop()


@async_test
async def test_replicated_service_delete_removes_tasks():
    clock = FakeClock()
    store = MemoryStore(clock=clock.now)
    orch = ReplicatedOrchestrator(store, clock=clock)
    await orch.start()
    svc = make_service(replicas=2)
    await store.update(lambda tx: tx.create(svc))
    await pump(clock)
    assert len(store.find("task", ByService(svc.id))) == 2
    await store.update(lambda tx: tx.delete("service", svc.id))
    await pump(clock)
    assert store.find("task", ByService(svc.id)) == []
    await orch.stop()


@async_test
async def test_restart_on_failure_with_delay():
    clock = FakeClock()
    store = MemoryStore(clock=clock.now)
    orch = ReplicatedOrchestrator(store, clock=clock)
    await orch.start()
    svc = make_service(replicas=1, restart=RestartPolicy(
        condition=RestartCondition.ANY, delay=3.0))
    await store.update(lambda tx: tx.create(svc))
    await pump(clock)
    (task,) = live_tasks(store, svc.id)

    # simulate failure
    def fail(tx):
        t = tx.get("task", task.id)
        t.status.state = TaskState.FAILED
        tx.update(t)
    await store.update(fail)
    await pump(clock)
    # replacement parked in READY until the delay elapses
    live = live_tasks(store, svc.id)
    assert len(live) == 1 and live[0].id != task.id
    assert live[0].desired_state == TaskState.READY
    old = store.get("task", task.id)
    assert old.desired_state == TaskState.SHUTDOWN
    # delay elapses -> promoted to RUNNING
    await pump(clock, seconds=3.5)
    assert store.get("task", live[0].id).desired_state == TaskState.RUNNING
    await orch.stop()


@async_test
async def test_restart_condition_none_does_not_restart():
    clock = FakeClock()
    store = MemoryStore(clock=clock.now)
    orch = ReplicatedOrchestrator(store, clock=clock)
    await orch.start()
    svc = make_service(replicas=1, restart=RestartPolicy(
        condition=RestartCondition.NONE))
    await store.update(lambda tx: tx.create(svc))
    await pump(clock)
    (task,) = live_tasks(store, svc.id)

    def complete(tx):
        t = tx.get("task", task.id)
        t.status.state = TaskState.COMPLETE
        tx.update(t)
    await store.update(complete)
    await pump(clock)
    assert live_tasks(store, svc.id) == []
    await orch.stop()


@async_test
async def test_restart_max_attempts():
    clock = FakeClock()
    store = MemoryStore(clock=clock.now)
    orch = ReplicatedOrchestrator(store, clock=clock)
    await orch.start()
    svc = make_service(replicas=1, restart=RestartPolicy(
        condition=RestartCondition.ANY, delay=0.0, max_attempts=2))
    await store.update(lambda tx: tx.create(svc))
    await pump(clock)

    for round_ in range(3):
        live = live_tasks(store, svc.id)
        if not live:
            break
        def fail(tx, tid=live[0].id):
            t = tx.get("task", tid)
            if t is not None and not common.in_terminal_state(t):
                t.status.state = TaskState.FAILED
                tx.update(t)
        await store.update(fail)
        await pump(clock, seconds=0.1)
        await pump(clock, seconds=0.1)
    # two restarts allowed, third failure leaves nothing live
    assert live_tasks(store, svc.id) == []
    await orch.stop()


@async_test
async def test_restart_history_resets_on_spec_change():
    """A slot that exhausted max_attempts restarts again once the task
    spec changes (reference shouldRestart restart.go:223 specVersion
    check) — otherwise a service update fixing a broken image could never
    revive the slot."""
    clock = FakeClock()
    store = MemoryStore(clock=clock.now)
    sup = RestartSupervisor(store, clock=clock)
    svc = make_service(replicas=1, restart=RestartPolicy(
        condition=RestartCondition.ANY, delay=0.0, max_attempts=1))
    t1 = common.new_task(None, svc, slot=1)
    t1.status.state = TaskState.FAILED
    await store.update(lambda tx: tx.create(t1))
    assert sup.should_restart(t1, svc)
    await store.update(lambda tx: sup.restart(tx, None, svc, t1))
    await pump(clock)

    t2 = [t for t in store.find("task") if t.id != t1.id][0]
    t2.status.state = TaskState.FAILED
    assert not sup.should_restart(t2, svc)   # strike count exhausted

    svc.spec.task.container.image = "nginx:2"   # the operator's fix
    t3 = common.new_task(None, svc, slot=1)
    t3.status.state = TaskState.FAILED
    assert sup.should_restart(t3, svc)       # fresh history under new spec

    # explicit clear (service removal) also wipes the slot's strikes
    sup.clear_service_history(svc.id)
    assert sup.should_restart(t2, svc)
    await sup.stop()


@async_test
async def test_restart_waits_for_old_task_to_stop():
    """The replacement is held in READY past its delay until the old task
    actually stops (reference DelayStart waitStop restart.go:169) — a slot
    never runs two tasks concurrently during a slow shutdown."""
    clock = FakeClock()
    store = MemoryStore(clock=clock.now)
    sup = RestartSupervisor(store, clock=clock)
    svc = make_service(replicas=1, restart=RestartPolicy(
        condition=RestartCondition.ANY, delay=0.0))
    node = make_node(1)
    t1 = common.new_task(None, svc, slot=1)
    t1.node_id = node.id
    t1.status.state = TaskState.RUNNING   # still up while being replaced

    def setup(tx):
        tx.create(node)
        tx.create(t1)
        sup.restart(tx, None, svc, t1)
    await store.update(setup)
    await pump(clock, seconds=0.2)

    repl = [t for t in store.find("task") if t.id != t1.id][0]
    assert store.get("task", repl.id).desired_state == TaskState.READY

    def stop_old(tx):
        t = tx.get("task", t1.id)
        t.status.state = TaskState.SHUTDOWN
        tx.update(t)
    await store.update(stop_old)
    await pump(clock, seconds=0.2)
    assert store.get("task", repl.id).desired_state == TaskState.RUNNING
    await sup.stop()


@async_test
async def test_restart_history_keyed_by_replacement_spec():
    """The strike is recorded under the REPLACEMENT's spec key: when a
    task running an old spec fails after a service update, its replacement
    is built from the new spec, and the new spec's failures must
    accumulate — keying by the failed task's spec would make every
    replacement look history-free and max_attempts would never trip."""
    clock = FakeClock()
    store = MemoryStore(clock=clock.now)
    sup = RestartSupervisor(store, clock=clock)
    svc = make_service(replicas=1, restart=RestartPolicy(
        condition=RestartCondition.ANY, delay=0.0, max_attempts=1))
    t1 = common.new_task(None, svc, slot=1)        # runs spec v1
    t1.status.state = TaskState.FAILED
    await store.update(lambda tx: tx.create(t1))

    svc.spec.task.container.image = "nginx:2"      # update lands before
    await store.update(lambda tx: sup.restart(tx, None, svc, t1))  # failure
    await pump(clock)

    t2 = [t for t in store.find("task") if t.id != t1.id][0]  # runs v2
    t2.status.state = TaskState.FAILED
    # the v2 slot already burned its one attempt (recorded at t1's restart)
    assert not sup.should_restart(t2, svc)
    await sup.stop()


@async_test
async def test_restart_wait_survives_watcher_close():
    """If the store's event bus shuts down while the replacement waits for
    the old task, the wait treats it as terminal and promotes — instead of
    re-arming a get() that fails instantly (busy loop with an unretrieved
    exception) until the old-task timeout."""
    clock = FakeClock()
    store = MemoryStore(clock=clock.now)
    sup = RestartSupervisor(store, clock=clock)
    svc = make_service(replicas=1, restart=RestartPolicy(
        condition=RestartCondition.ANY, delay=0.0))
    node = make_node(1)
    t1 = common.new_task(None, svc, slot=1)
    t1.node_id = node.id
    t1.status.state = TaskState.RUNNING

    def setup(tx):
        tx.create(node)
        tx.create(t1)
        sup.restart(tx, None, svc, t1)
    await store.update(setup)
    await pump(clock, seconds=0.2)
    repl = [t for t in store.find("task") if t.id != t1.id][0]
    assert store.get("task", repl.id).desired_state == TaskState.READY

    store.queue.close()   # teardown: every watcher's get() -> WatcherClosed
    await pump(clock)     # no clock advance: must not need the timeout
    assert store.get("task", repl.id).desired_state == TaskState.RUNNING
    await sup.stop()


@async_test
async def test_restart_no_wait_when_node_down():
    """A dead node can't report its task stopped: the replacement starts
    immediately (reference restart.go:173 waitStop=false)."""
    clock = FakeClock()
    store = MemoryStore(clock=clock.now)
    sup = RestartSupervisor(store, clock=clock)
    svc = make_service(replicas=1, restart=RestartPolicy(
        condition=RestartCondition.ANY, delay=0.0))
    node = make_node(1)
    node.status.state = NodeState.DOWN
    t1 = common.new_task(None, svc, slot=1)
    t1.node_id = node.id
    t1.status.state = TaskState.RUNNING   # stale: the node is gone

    def setup(tx):
        tx.create(node)
        tx.create(t1)
        sup.restart(tx, None, svc, t1)
    await store.update(setup)
    await pump(clock, seconds=0.1)
    repl = [t for t in store.find("task") if t.id != t1.id][0]
    assert store.get("task", repl.id).desired_state == TaskState.RUNNING
    await sup.stop()


@async_test
async def test_drained_node_skips_restart_delay():
    """Evacuation replacements are not rate-limited: the restart delay is
    skipped when the old task's node is drained (reference restart.go:156)."""
    clock = FakeClock()
    store = MemoryStore(clock=clock.now)
    sup = RestartSupervisor(store, clock=clock)
    svc = make_service(replicas=1, restart=RestartPolicy(
        condition=RestartCondition.ANY, delay=30.0))
    node = make_node(1)
    node.spec.availability = NodeAvailability.DRAIN
    t1 = common.new_task(None, svc, slot=1)
    t1.node_id = node.id
    t1.status.state = TaskState.SHUTDOWN   # already stopped by the agent

    def setup(tx):
        tx.create(node)
        tx.create(t1)
        sup.restart(tx, None, svc, t1)
    await store.update(setup)
    await pump(clock, seconds=0.1)   # far less than the 30s delay
    repl = [t for t in store.find("task") if t.id != t1.id][0]
    assert store.get("task", repl.id).desired_state == TaskState.RUNNING
    await sup.stop()


@async_test
async def test_checktasks_rearm_keeps_old_task_wait_and_credits_delay():
    """After a leader change, check_tasks re-arms parked READY replacements
    WITH the slot's still-draining predecessor as the old-task wait (an
    improvement over reference init.go:94, which passes nil there) and
    credits time already waited against the restart delay (init.go:74-87)."""
    from swarmkit_tpu.manager.orchestrator.taskinit import check_tasks

    clock = FakeClock()
    await clock.advance(10.0)   # a nonzero epoch (0.0 reads as "unset")
    store = MemoryStore(clock=clock.now)
    sup = RestartSupervisor(store, clock=clock)
    svc = make_service(replicas=1, restart=RestartPolicy(
        condition=RestartCondition.ANY, delay=100.0))
    node = make_node(1)
    old = common.new_task(None, svc, slot=1)
    old.node_id = node.id
    old.status.state = TaskState.RUNNING       # still draining
    old.desired_state = int(TaskState.SHUTDOWN)
    parked = common.new_task(None, svc, slot=1)
    parked.desired_state = int(TaskState.READY)
    parked.status.timestamp = clock.now()       # failure happened "now"

    def setup(tx):
        tx.create(svc)
        tx.create(node)
        tx.create(old)
        tx.create(parked)
    await store.update(setup)

    await clock.advance(99.9)                   # pre-failover waiting
    await check_tasks(store, sup, Mode.REPLICATED)
    # delay is credited: only ~0.1s remains, NOT a fresh 100s
    await pump(clock, seconds=1.0)
    # ...but the old task still runs, so the replacement stays READY
    assert store.get("task", parked.id).desired_state == TaskState.READY

    def stop_old(tx):
        t = tx.get("task", old.id)
        t.status.state = TaskState.SHUTDOWN
        tx.update(t)
    await store.update(stop_old)
    await pump(clock, seconds=0.2)
    assert store.get("task", parked.id).desired_state == TaskState.RUNNING
    await sup.stop()


@async_test
async def test_rolling_update_stop_first():
    clock = FakeClock()
    store = MemoryStore(clock=clock.now)
    orch = ReplicatedOrchestrator(store, clock=clock)
    await orch.start()
    svc = make_service(replicas=3, update=UpdateConfig(parallelism=1,
                                                       monitor=0.2))
    await store.update(lambda tx: tx.create(svc))
    await pump(clock)
    old_ids = {t.id for t in live_tasks(store, svc.id)}

    # mark tasks running (simulated agents)
    def run_all(tx):
        for t in store.find("task", ByService(svc.id)):
            cur = tx.get("task", t.id)
            cur.status.state = TaskState.RUNNING
            tx.update(cur)
    await store.update(run_all)
    await pump(clock)

    # change the image -> dirty slots -> rolling update
    svc2 = store.get("service", svc.id)
    svc2.spec.task.container.image = "nginx:2"
    await store.update(lambda tx: tx.update(svc2))
    await pump(clock)

    # drive: as updater shuts down old tasks, "agents" report them shutdown;
    # new tasks get reported running
    for _ in range(60):
        def agent_sim(tx):
            for t in store.find("task", ByService(svc.id)):
                cur = tx.get("task", t.id)
                if cur is None:
                    continue
                if cur.desired_state == TaskState.SHUTDOWN \
                        and cur.status.state < TaskState.SHUTDOWN:
                    cur.status.state = TaskState.SHUTDOWN
                    tx.update(cur)
                elif cur.desired_state == TaskState.RUNNING \
                        and cur.status.state < TaskState.RUNNING:
                    cur.status.state = TaskState.RUNNING
                    tx.update(cur)
        await store.update(agent_sim)
        await pump(clock, seconds=0.1)
        new_live = live_tasks(store, svc.id)
        s = store.get("service", svc.id)
        if len(new_live) == 3 and all(
                t.spec.container.image == "nginx:2" for t in new_live
                ) and all(t.id not in old_ids for t in new_live) \
                and s.update_status is not None \
                and s.update_status.state == "completed":
            break
    else:
        s = store.get("service", svc.id)
        raise AssertionError(
            f"update did not converge (status="
            f"{s.update_status and s.update_status.state}): "
            f"{[(t.id, t.spec.container.image, int(t.status.state)) for t in live_tasks(store, svc.id)]}")
    await orch.stop()


@async_test
async def test_update_reuses_existing_clean_task_in_half_updated_slot():
    """If a previous updater died after creating the new-spec task but
    before cleaning the slot, the next pass finishes the slot — shutting
    down the dirty task and starting the parked clean one — instead of
    churning a THIRD task (reference worker/useExistingTask
    updater.go:313-485)."""
    from swarmkit_tpu.manager.orchestrator.restart import RestartSupervisor
    from swarmkit_tpu.manager.orchestrator.update import UpdateSupervisor

    clock = FakeClock()
    store = MemoryStore(clock=clock.now)
    restart_sup = RestartSupervisor(store, clock=clock)
    upd = UpdateSupervisor(store, restart_sup, clock=clock)
    svc = make_service(replicas=1, image="nginx:2",
                       update=UpdateConfig(parallelism=1, monitor=0.3))

    old = common.new_task(None, svc, slot=1)
    old.spec.container.image = "nginx:1"          # dirty vs the new spec
    old.status.state = TaskState.RUNNING
    clean = common.new_task(None, svc, slot=1)     # the stranded new task
    clean.desired_state = int(TaskState.READY)

    def setup(tx):
        tx.create(svc)
        tx.create(old)
        tx.create(clean)
    await store.update(setup)

    upd.update(None, svc, [[old, clean]])
    await pump(clock, seconds=0.1)
    # old drains; agent reports it stopped
    assert store.get("task", old.id).desired_state == TaskState.SHUTDOWN

    def agent_stop(tx):
        t = tx.get("task", old.id)
        t.status.state = TaskState.SHUTDOWN
        tx.update(t)
    await store.update(agent_stop)

    for _ in range(20):
        await pump(clock, seconds=0.05)
        c = store.get("task", clean.id)
        if c.desired_state == TaskState.RUNNING:
            break
    assert store.get("task", clean.id).desired_state == TaskState.RUNNING
    # no third task was created
    assert len(store.find("task", ByService(svc.id))) == 2
    await upd.stop()
    await restart_sup.stop()


@async_test
async def test_paused_update_stays_paused_until_operator_acts():
    """failure_action=PAUSE halts the rollout AND keeps it halted across
    later reconciles (reference Updater.Run updater.go:130 refuses paused
    updates); only the operator's next service-update — which resets
    update_status (controlapi) — resumes it."""
    clock = FakeClock()
    store = MemoryStore(clock=clock.now)
    orch = ReplicatedOrchestrator(store, clock=clock)
    await orch.start()
    svc = make_service(replicas=3, update=UpdateConfig(
        parallelism=1, monitor=0.2, max_failure_ratio=0.0))
    await store.update(lambda tx: tx.create(svc))
    await pump(clock)

    def run_all(tx):
        for t in store.find("task", ByService(svc.id)):
            cur = tx.get("task", t.id)
            cur.status.state = TaskState.RUNNING
            tx.update(cur)
    await store.update(run_all)
    await pump(clock)

    # dirty the spec; the FIRST replacement task fails -> paused
    svc2 = store.get("service", svc.id)
    svc2.spec.task.container.image = "nginx:2"
    await store.update(lambda tx: tx.update(svc2))
    for _ in range(40):
        def agent_fail_new(tx):
            for t in store.find("task", ByService(svc.id)):
                cur = tx.get("task", t.id)
                if cur is None:
                    continue
                if cur.spec.container.image == "nginx:2" \
                        and cur.desired_state >= TaskState.READY \
                        and not common.in_terminal_state(cur):
                    cur.status.state = TaskState.FAILED
                    tx.update(cur)
                elif cur.desired_state == TaskState.SHUTDOWN \
                        and cur.status.state < TaskState.SHUTDOWN:
                    cur.status.state = TaskState.SHUTDOWN
                    tx.update(cur)
        await store.update(agent_fail_new)
        await pump(clock, seconds=0.1)
        s = store.get("service", svc.id)
        if s.update_status is not None and s.update_status.state == "paused":
            break
    else:
        raise AssertionError("update never paused")

    # old tasks on the old image are untouched beyond the first slot
    n_after_pause = len(store.find("task", ByService(svc.id)))

    # later reconciles (task events, ticks) must NOT resume the rollout
    await pump(clock, seconds=2.0)
    def poke(tx):   # any store event that wakes the orchestrator
        s = tx.get("service", svc.id)
        tx.update(s)
    await store.update(poke)
    await pump(clock, seconds=2.0)
    s = store.get("service", svc.id)
    assert s.update_status.state == "paused"
    assert len(store.find("task", ByService(svc.id))) == n_after_pause, \
        "paused update created more replacement tasks"

    # the operator updates the service again: status resets, rollout runs
    def operator_update(tx):
        s = tx.get("service", svc.id)
        s.spec.task.container.image = "nginx:3"
        s.update_status = None       # what controlapi.update_service does
        tx.update(s)
    await store.update(operator_update)
    for _ in range(60):
        def agent_ok(tx):
            for t in store.find("task", ByService(svc.id)):
                cur = tx.get("task", t.id)
                if cur is None:
                    continue
                if cur.desired_state == TaskState.SHUTDOWN \
                        and cur.status.state < TaskState.SHUTDOWN:
                    cur.status.state = TaskState.SHUTDOWN
                    tx.update(cur)
                elif cur.desired_state == TaskState.RUNNING \
                        and cur.status.state < TaskState.RUNNING \
                        and cur.spec.container.image == "nginx:3":
                    cur.status.state = TaskState.RUNNING
                    tx.update(cur)
        await store.update(agent_ok)
        await pump(clock, seconds=0.1)
        live = live_tasks(store, svc.id)
        if len(live) == 3 and all(t.spec.container.image == "nginx:3"
                                  for t in live):
            break
    else:
        raise AssertionError("resumed update did not converge")
    await orch.stop()


@async_test
async def test_global_orchestrator_one_task_per_node():
    clock = FakeClock()
    store = MemoryStore(clock=clock.now)
    orch = GlobalOrchestrator(store, clock=clock)
    await store.update(lambda tx: [tx.create(make_node(1)),
                                   tx.create(make_node(2))])
    await orch.start()
    svc = make_service(name="mon", mode=Mode.GLOBAL)
    await store.update(lambda tx: tx.create(svc))
    await pump(clock)
    tasks = live_tasks(store, svc.id)
    assert sorted(t.node_id for t in tasks) == ["node1", "node2"]

    # new node joins -> new task
    await store.update(lambda tx: tx.create(make_node(3)))
    await pump(clock)
    assert sorted(t.node_id for t in live_tasks(store, svc.id)) == \
        ["node1", "node2", "node3"]

    # node drained -> task shut down
    n3 = store.get("node", "node3")
    n3.spec.availability = NodeAvailability.DRAIN
    await store.update(lambda tx: tx.update(n3))
    await pump(clock)
    assert sorted(t.node_id for t in live_tasks(store, svc.id)) == \
        ["node1", "node2"]
    await orch.stop()


@async_test
async def test_task_reaper_retention():
    clock = FakeClock()
    store = MemoryStore(clock=clock.now)
    reaper = TaskReaper(store, clock=clock)
    await reaper.start()
    svc = make_service(replicas=1)
    await store.update(lambda tx: tx.create(svc))
    # create 8 dead tasks in the same slot (history) + 1 live
    def seed(tx):
        for i in range(8):
            t = common.new_task(None, svc, slot=1)
            t.status.state = TaskState.FAILED
            t.status.timestamp = float(i)
            t.desired_state = int(TaskState.SHUTDOWN)
            tx.create(t)
        live = common.new_task(None, svc, slot=1)
        tx.create(live)
    await store.update(seed)
    await pump(clock)
    remaining = store.find("task", ByService(svc.id))
    dead = [t for t in remaining if common.in_terminal_state(t)]
    assert len(dead) == 5  # default retention
    # oldest were deleted first
    assert sorted(t.status.timestamp for t in dead) == [3.0, 4.0, 5.0, 6.0, 7.0]
    await reaper.stop()


@async_test
async def test_task_reaper_negative_retention_never_cleans():
    """A negative TaskHistoryRetentionLimit disables history cleanup
    entirely (reference task_reaper.go:298) — it must not be arithmetic
    that deletes MORE; an explicit 0 keeps NO history."""
    clock = FakeClock()
    store = MemoryStore(clock=clock.now)
    reaper = TaskReaper(store, clock=clock)
    await reaper.start()
    svc = make_service(replicas=1)
    cl = make_cluster_with_retention(-1)
    await store.update(lambda tx: [tx.create(cl), tx.create(svc)])

    def seed(tx):
        for i in range(8):
            t = common.new_task(None, svc, slot=1)
            t.status.state = TaskState.FAILED
            t.status.timestamp = float(i)
            t.desired_state = int(TaskState.SHUTDOWN)
            tx.create(t)
    await store.update(seed)
    await pump(clock)
    assert len(store.find("task", ByService(svc.id))) == 8

    # flip to an explicit 0: ALL dead history goes
    def zero(tx):
        c = tx.get("cluster", "c1").copy()
        c.spec.orchestration.task_history_retention_limit = 0
        tx.update(c)

    def poke(tx):   # dirty the slot again via a fresh dead task
        t = common.new_task(None, svc, slot=1)
        t.status.state = TaskState.FAILED
        t.desired_state = int(TaskState.SHUTDOWN)
        tx.create(t)
    await store.update(zero)
    await store.update(poke)
    await pump(clock)
    assert len(store.find("task", ByService(svc.id))) == 0
    await reaper.stop()


@async_test
async def test_task_reaper_max_attempts_overrides_retention():
    """With restart max_attempts set, the reaper keeps max_attempts+1
    dead tasks regardless of the cluster retention limit, so restart
    history is reconstructible after a leader change (task_reaper.go:295)."""
    clock = FakeClock()
    store = MemoryStore(clock=clock.now)
    reaper = TaskReaper(store, clock=clock)
    await reaper.start()
    svc = make_service(replicas=1, restart=RestartPolicy(
        condition=RestartCondition.ANY, max_attempts=6))
    cl = make_cluster_with_retention(2)
    await store.update(lambda tx: [tx.create(cl), tx.create(svc)])

    def seed(tx):
        for i in range(10):
            t = common.new_task(None, svc, slot=1)
            t.status.state = TaskState.FAILED
            t.status.timestamp = float(i)
            t.desired_state = int(TaskState.SHUTDOWN)
            tx.create(t)
    await store.update(seed)
    await pump(clock)
    dead = [t for t in store.find("task", ByService(svc.id))
            if common.in_terminal_state(t)]
    assert len(dead) == 7   # max_attempts + 1, not the cluster's 2
    await reaper.stop()


@async_test
async def test_task_reaper_trims_never_assigned_history():
    """Tasks that will NEVER run (desired terminal while still unassigned
    — no agent will ever move them) count as cleanable slot history
    (taskWillNeverRun, task_reaper.go:344)."""
    clock = FakeClock()
    store = MemoryStore(clock=clock.now)
    reaper = TaskReaper(store, clock=clock)
    await reaper.start()
    svc = make_service(replicas=1)
    await store.update(lambda tx: tx.create(svc))

    def seed(tx):
        for i in range(8):
            t = common.new_task(None, svc, slot=1)   # status NEW, no node
            t.status.timestamp = float(i)
            t.desired_state = int(TaskState.SHUTDOWN)
            tx.create(t)
    await store.update(seed)
    await pump(clock)
    assert len(store.find("task", ByService(svc.id))) == 5  # retention
    await reaper.stop()


def make_cluster_with_retention(limit):
    from swarmkit_tpu.api.objects import Cluster
    from swarmkit_tpu.api.specs import ClusterSpec, OrchestrationConfig

    return Cluster(id="c1", spec=ClusterSpec(
        annotations=Annotations(name="default"),
        orchestration=OrchestrationConfig(
            task_history_retention_limit=limit)))


@async_test
async def test_task_reaper_remove_desired():
    """Desired-REMOVE tasks: an ASSIGNED one waits for the agent's
    shutdown; an UNASSIGNED one (state < ASSIGNED — no agent will ever
    touch it) is reaped immediately (reference task_reaper.go:181; the
    Tasks.tla reaper exceptions <<new,null>>/<<pending,null>>)."""
    clock = FakeClock()
    store = MemoryStore(clock=clock.now)
    reaper = TaskReaper(store, clock=clock)
    await reaper.start()
    svc = make_service(replicas=1)
    t = common.new_task(None, svc, slot=1)
    t.desired_state = int(TaskState.REMOVE)
    t.status.state = TaskState.ASSIGNED
    t.node_id = "node1"
    await store.update(lambda tx: (tx.create(svc), tx.create(t)))
    await pump(clock)
    assert store.get("task", t.id) is not None  # assigned: not terminal yet

    def shutdown(tx):
        cur = tx.get("task", t.id)
        cur.status.state = TaskState.SHUTDOWN
        tx.update(cur)
    await store.update(shutdown)
    await pump(clock)
    assert store.get("task", t.id) is None

    # unassigned (NEW/PENDING) + desired REMOVE: reaped right away —
    # previously these leaked forever
    t2 = common.new_task(None, svc, slot=2)
    t2.desired_state = int(TaskState.REMOVE)
    assert t2.status.state < TaskState.ASSIGNED
    await store.update(lambda tx: tx.create(t2))
    await pump(clock)
    assert store.get("task", t2.id) is None
    await reaper.stop()


@async_test
async def test_constraint_enforcer_evicts_on_label_change():
    clock = FakeClock()
    store = MemoryStore(clock=clock.now)
    enforcer = ConstraintEnforcer(store, clock=clock)
    await enforcer.start()
    node = make_node(1)
    node.spec.annotations.labels["zone"] = "a"
    svc = make_service(replicas=1, constraints=["node.labels.zone==a"])
    task = common.new_task(None, svc, slot=1, node_id="node1")
    task.node_id = "node1"
    task.status.state = TaskState.RUNNING
    await store.update(lambda tx: (tx.create(node), tx.create(svc),
                                   tx.create(task)))
    await pump(clock)
    assert store.get("task", task.id).desired_state == TaskState.RUNNING

    # label changes -> constraint violated -> evicted
    n = store.get("node", "node1")
    n.spec.annotations.labels["zone"] = "b"
    await store.update(lambda tx: tx.update(n))
    await pump(clock)
    assert store.get("task", task.id).desired_state == TaskState.SHUTDOWN
    await enforcer.stop()


@async_test
async def test_global_service_spec_update_rolls_out():
    """Regression: a global service image change must reach every node."""
    clock = FakeClock()
    store = MemoryStore(clock=clock.now)
    orch = GlobalOrchestrator(store, clock=clock)
    await store.update(lambda tx: [tx.create(make_node(1)),
                                   tx.create(make_node(2))])
    await orch.start()
    svc = make_service(name="mon", mode=Mode.GLOBAL,
                       update=UpdateConfig(parallelism=2, monitor=0.2))
    await store.update(lambda tx: tx.create(svc))
    await pump(clock)

    def run_all(tx):
        for t in store.find("task", ByService(svc.id)):
            cur = tx.get("task", t.id)
            cur.status.state = TaskState.RUNNING
            tx.update(cur)
    await store.update(run_all)
    await pump(clock)

    svc2 = store.get("service", svc.id)
    svc2.spec.task.container.image = "nginx:2"
    await store.update(lambda tx: tx.update(svc2))
    await pump(clock)

    for _ in range(60):
        def agent_sim(tx):
            for t in store.find("task", ByService(svc.id)):
                cur = tx.get("task", t.id)
                if cur is None:
                    continue
                if cur.desired_state == TaskState.SHUTDOWN \
                        and cur.status.state < TaskState.SHUTDOWN:
                    cur.status.state = TaskState.SHUTDOWN
                    tx.update(cur)
                elif cur.desired_state == TaskState.RUNNING \
                        and cur.status.state < TaskState.RUNNING:
                    cur.status.state = TaskState.RUNNING
                    tx.update(cur)
        await store.update(agent_sim)
        await pump(clock, seconds=0.1)
        live = live_tasks(store, svc.id)
        if len(live) == 2 and all(
                t.spec.container.image == "nginx:2" for t in live):
            break
    else:
        raise AssertionError(
            f"global update did not roll out: "
            f"{[(t.node_id, t.spec.container.image) for t in live_tasks(store, svc.id)]}")
    await orch.stop()


@async_test
async def test_constraint_enforcer_evicts_on_shrunk_resources():
    from swarmkit_tpu.api import Resources, ResourceRequirements
    from swarmkit_tpu.api.types import NodeResources

    clock = FakeClock()
    store = MemoryStore(clock=clock.now)
    enforcer = ConstraintEnforcer(store, clock=clock)

    node = make_node(1)
    node.description.resources = NodeResources(nano_cpus=4_000_000_000,
                                               memory_bytes=8 << 30)
    await store.update(lambda tx: tx.create(node))
    await enforcer.start()

    from swarmkit_tpu.api import Task, TaskStatus
    def mk(i):
        return Task(id=f"t{i}", service_id="s", slot=i, node_id="node1",
                    spec=TaskSpec(resources=ResourceRequirements(
                        reservations=Resources(nano_cpus=1_500_000_000,
                                               memory_bytes=3 << 30))),
                    status=TaskStatus(state=TaskState.RUNNING),
                    desired_state=int(TaskState.RUNNING))
    await store.update(lambda tx: [tx.create(mk(1)), tx.create(mk(2))])

    # node re-registers with half the memory -> one task no longer fits
    n = store.get("node", "node1")
    n.description.resources = NodeResources(nano_cpus=4_000_000_000,
                                            memory_bytes=4 << 30)
    await store.update(lambda tx: tx.update(n))
    await pump(clock)
    shutdown = [t for t in store.find("task")
                if t.desired_state == TaskState.SHUTDOWN]
    live = [t for t in store.find("task")
            if t.desired_state == TaskState.RUNNING]
    assert len(shutdown) == 1 and len(live) == 1
    await enforcer.stop()


@async_test
async def test_task_reaper_serviceless_orphaned():
    """A serviceless task (network-attachment style) that goes ORPHANED has
    no service to reconcile it away — the reaper deletes it directly
    (reference task_reaper.go:174-175)."""
    from swarmkit_tpu.api import Task, TaskStatus

    clock = FakeClock()
    store = MemoryStore(clock=clock.now)
    reaper = TaskReaper(store, clock=clock)
    await reaper.start()
    t = Task(id="att1", service_id="", node_id="node1",
             status=TaskStatus(state=TaskState.RUNNING),
             desired_state=int(TaskState.RUNNING))
    await store.update(lambda tx: tx.create(t))
    await pump(clock)
    assert store.get("task", "att1") is not None

    def orphan(tx):
        cur = tx.get("task", "att1")
        cur.status.state = TaskState.ORPHANED
        tx.update(cur)
    await store.update(orphan)
    await pump(clock)
    assert store.get("task", "att1") is None
    await reaper.stop()

"""Test configuration.

Force JAX onto the CPU backend with 8 virtual devices BEFORE jax is imported
anywhere, so sharding/multi-chip tests run without TPU hardware (the driver
separately dry-runs the multichip path the same way).
"""

import os

os.environ["JAX_PLATFORMS"] = "cpu"
flags = os.environ.get("XLA_FLAGS", "")
if "xla_force_host_platform_device_count" not in flags:
    os.environ["XLA_FLAGS"] = (
        flags + " --xla_force_host_platform_device_count=8").strip()

# The image's sitecustomize registers the axon TPU platform and overrides the
# env var, so pin the platform via config as well (works pre-backend-init).
import jax  # noqa: E402

jax.config.update("jax_platforms", "cpu")

import asyncio
import functools

import pytest


from swarmkit_tpu.ca.certificates import HAVE_CRYPTOGRAPHY  # noqa: E402

# x509/TLS tests cannot run where the `cryptography` package is absent;
# everything else runs against the hashlib-backed encryption fallback.
requires_cryptography = pytest.mark.skipif(
    not HAVE_CRYPTOGRAPHY,
    reason="needs the 'cryptography' package (x509/TLS identities)")


def async_test(fn):
    """Run an async test function to completion on a fresh event loop."""

    @functools.wraps(fn)
    def wrapper(*args, **kwargs):
        return asyncio.run(fn(*args, **kwargs))

    return wrapper


@pytest.fixture
def run():
    return asyncio.run

"""The differential correctness gate (SURVEY §7 stage 4): the batched XLA
kernel and the host golden core (swarmkit_tpu.raft.core, mirroring vendored
etcd/raft Step semantics at vendor/.../raft/raft.go:679-1060) are driven with
IDENTICAL timeout/drop/crash/proposal schedules and compared per tick, field
by field: term, vote, role, lead, last, commit, applied, apply_chk (the
applied-log-content checksum — equality implies identical applied prefixes).

The scheduler that makes core.py comparable tick-for-tick lives in
swarmkit_tpu.raft.sim.oracle, together with the single documented list of
intentional kernel divergences (D1-D5) and how each is masked.
"""

from __future__ import annotations

from functools import partial

import jax
import numpy as np
import pytest

from swarmkit_tpu.raft.sim import SimConfig, init_state
from swarmkit_tpu.raft.sim.kernel import (
    propose, propose_conf, step, transfer_leadership,
)
from swarmkit_tpu.raft.sim.oracle import OracleCluster

_step = jax.jit(step, static_argnames=("cfg",))
_propose = jax.jit(propose, static_argnames=("cfg",))
_propose_conf = jax.jit(propose_conf, static_argnames=("cfg",))

# One compiled config per cluster size (cfg is a static jit arg; varying the
# schedule, not the shapes, keeps the suite to three compilations).
CFG3 = SimConfig(n=3, log_len=64, window=8, apply_batch=16, max_props=8,
                 keep=4, election_tick=10, seed=1234)
CFG5 = SimConfig(n=5, log_len=64, window=8, apply_batch=16, max_props=8,
                 keep=4, election_tick=10, seed=77)
CFG7 = SimConfig(n=7, log_len=64, window=8, apply_batch=16, max_props=8,
                 keep=4, election_tick=12, seed=9)


def kernel_view(state) -> dict:
    return {
        "term": np.asarray(state.term),
        "vote": np.asarray(state.vote),
        "role": np.asarray(state.role),
        "lead": np.asarray(state.lead),
        "last": np.asarray(state.last),
        "commit": np.asarray(state.commit),
        "applied": np.asarray(state.applied),
        "apply_chk": np.asarray(state.apply_chk),
        "member": np.asarray(state.member),
    }


def run_differential(cfg: SimConfig, n_ticks: int, seed: int,
                     drop_rate: float = 0.0, crash_prob: float = 0.0,
                     prop_prob: float = 0.5, partition_at: tuple = (),
                     crash_leader_every: int = 0,
                     transfer_every: int = 0,
                     conf_every: int = 0, voters=None,
                     min_members: int = 3,
                     remove_leader_every: int = 0,
                     sleep_node: tuple = ()) -> dict:
    """Drive kernel + oracle on one random schedule; assert per-tick equality.
    Returns summary stats (max commit etc.) so callers can assert progress.

    conf_every: every k ticks propose ONE membership change through the
    replicated log (kernel propose_conf / oracle CONF_CHANGE entry) — a
    remove of a random non-leader member while the intended config stays
    above `min_members`, else a re-add of a previously removed row.

    remove_leader_every: every k ticks the SITTING LEADER proposes its own
    removal (the hardest membership path: self-excluded commit quorum and
    CheckQuorum, ProposalDropped once applied); the shell then stops the
    removed process a few ticks later (swarmkit removeMember -> node
    shutdown, raft.go:2005) so the survivors elect.

    sleep_node: (row, start, wake) — force ONE follower down through the
    compaction window so it returns far enough behind that only the
    snapshot path can catch it up (reference territory: raft_test.go
    snapshot streaming / LogEntriesForSlowFollowers).
    """
    rng = np.random.default_rng(seed)
    n = cfg.n
    state = init_state(cfg, voters=voters)
    oracle = OracleCluster(cfg, voters=voters)

    alive = np.ones(n, bool)
    down_until = np.zeros(n, np.int64)
    # intended config for picking conf targets (actual membership follows
    # the committed log; this is only the scheduler's bookkeeping)
    intended = set(range(n) if voters is None else voters)
    removed = set(range(n)) - intended
    stop_at: dict = {}   # node -> tick of permanent shell stop

    for t in range(n_ticks):
        # -- crash schedule
        alive = down_until <= t
        for v, at in stop_at.items():
            if t >= at:
                alive[v] = False
        if crash_prob and rng.random() < crash_prob:
            victim = int(rng.integers(n))
            down_until[victim] = t + int(rng.integers(3, 25))
            alive[victim] = False
        if sleep_node and t == sleep_node[1]:
            down_until[sleep_node[0]] = sleep_node[2]
            alive[sleep_node[0]] = False
        if crash_leader_every and t > 0 and t % crash_leader_every == 0:
            kv = kernel_view(state)
            leaders = np.nonzero((kv["role"] == 2) & alive)[0]
            if len(leaders):
                victim = int(leaders[0])
                down_until[victim] = t + int(rng.integers(5, 20))
                alive[victim] = False

        # -- drop schedule (per-edge Bernoulli + optional block partition)
        drop = rng.random((n, n)) < drop_rate if drop_rate else np.zeros(
            (n, n), bool)
        if partition_at:
            start, end, cut = partition_at
            if start <= t < end:
                side = np.arange(n) < cut
                drop = drop | (side[:, None] != side[None, :])

        # -- leader-transfer schedule: ask the sitting leader to hand off
        if transfer_every and t > 0 and t % transfer_every == 0:
            kv = kernel_view(state)
            leaders = np.nonzero((kv["role"] == 2) & alive)[0]
            if len(leaders):
                ldr = int(leaders[0])
                tgt = int(rng.integers(n))
                state = transfer_leadership(state, cfg, ldr, tgt)
                oracle.transfer(ldr, tgt)

        # -- proposal schedule
        prop_count = 0
        payloads = np.zeros(cfg.max_props, np.uint32)
        if prop_prob and rng.random() < prop_prob:
            prop_count = int(rng.integers(1, cfg.max_props + 1))
            payloads[:prop_count] = rng.integers(
                1, 1 << 31, prop_count, dtype=np.uint32)

        # -- membership-change schedule (log-driven conf proposals)
        conf = None
        if remove_leader_every and t > 0 and t % remove_leader_every == 0 \
                and len(intended) > min_members:
            kv = kernel_view(state)
            leaders = np.nonzero((kv["role"] == 2) & alive)[0]
            lset = [int(x) for x in leaders if int(x) in intended]
            if lset:
                tgt = lset[0]
                conf = (tgt, True)
                intended.discard(tgt)
                removed.add(tgt)
                # shell stops the removed process after a grace window
                # (the entry must replicate first): swarmkit removeMember
                # -> node shutdown, raft.go:2005
                stop_at[tgt] = t + 8
        if conf is None and conf_every and t > 0 and t % conf_every == 0:
            kv = kernel_view(state)
            leaders = set(np.nonzero((kv["role"] == 2) & alive)[0].tolist())
            if removed and (len(intended) <= min_members
                            or rng.random() < 0.5):
                tgt = int(rng.choice(sorted(removed)))
                conf = (tgt, False)
                removed.discard(tgt)
                intended.add(tgt)
            else:
                cands = sorted(intended - leaders)
                if len(intended) > min_members and cands:
                    tgt = int(rng.choice(cands))
                    conf = (tgt, True)
                    intended.discard(tgt)
                    removed.add(tgt)

        # -- advance both sides with the identical schedule (proposals
        # consult liveness: clients cannot reach a crashed claimant)
        if prop_count:
            state = _propose(state, cfg, payloads,
                             np.asarray(prop_count, np.int32),
                             alive=np.asarray(alive))
        if conf is not None:
            state = _propose_conf(state, cfg,
                                  np.asarray(conf[0], np.int32),
                                  np.asarray(conf[1], bool),
                                  alive=np.asarray(alive))
        state = _step(state, cfg, alive=alive, drop=drop)
        oracle.tick(alive, drop, payloads, prop_count, conf)

        kv = kernel_view(state)
        ov = oracle.view()
        for f in ("term", "vote", "role", "lead", "last", "commit",
                  "applied", "apply_chk", "member"):
            ke, oe = kv[f], getattr(ov, f)
            assert np.array_equal(ke, oe), (
                f"seed={seed} tick={t} field={f}\n"
                f"  kernel: {ke}\n  oracle: {oe}\n"
                f"  terms k/o: {kv['term']}/{ov.term}\n"
                f"  roles k/o: {kv['role']}/{ov.role}")

    kv = kernel_view(state)
    return {"max_commit": int(kv["commit"].max()),
            "max_term": int(kv["term"].max())}


# ---------------------------------------------------------------------------
# ~200 randomized schedules across three cluster sizes. Each case mixes
# proposals with a different fault regime.
# ---------------------------------------------------------------------------

@pytest.mark.parametrize("seed", range(0, 60))
def test_differential_clean_and_light_drop_n3(seed):
    drop = [0.0, 0.05, 0.15][seed % 3]
    run_differential(CFG3, n_ticks=90, seed=seed, drop_rate=drop)


@pytest.mark.parametrize("seed", range(100, 160))
def test_differential_drop_and_crash_n5(seed):
    drop = [0.0, 0.1, 0.25][seed % 3]
    crash = [0.0, 0.05, 0.1][(seed // 3) % 3]
    run_differential(CFG5, n_ticks=90, seed=seed, drop_rate=drop,
                     crash_prob=crash)


@pytest.mark.parametrize("seed", range(200, 240))
def test_differential_heavy_faults_n7(seed):
    run_differential(CFG7, n_ticks=80, seed=seed, drop_rate=0.2,
                     crash_prob=0.08)


@pytest.mark.parametrize("seed", range(300, 320))
def test_differential_leader_crash_cycles(seed):
    """BASELINE config-4 regime: kill the sitting leader periodically."""
    stats = run_differential(CFG5, n_ticks=120, seed=seed,
                             crash_leader_every=30, prop_prob=0.7)
    assert stats["max_commit"] > 0


@pytest.mark.parametrize("seed", range(400, 410))
def test_differential_partition_heal(seed):
    """Block partition (minority cut off) then heal; both sides must track
    the same re-convergence tick-for-tick."""
    stats = run_differential(CFG5, n_ticks=120, seed=seed, drop_rate=0.05,
                             partition_at=(30, 70, 2))
    assert stats["max_commit"] > 0


@pytest.mark.parametrize("seed", range(500, 510))
def test_differential_compaction_snapshot(seed):
    """Heavy proposals against a small ring force compaction; a follower
    crashed through the compaction window must be caught up via the
    snapshot path identically on both sides."""
    rngseed = seed
    stats = run_differential(CFG3, n_ticks=150, seed=rngseed, prop_prob=0.9,
                             crash_prob=0.06)
    assert stats["max_commit"] > 20  # compaction pressure was reached


# Tiled log axis: the banded (log_chunk) kernel against the host golden
# core — the chunked C/E/F passes and their fallback branch must track the
# oracle exactly like the full-pass kernel does (it is also pinned against
# the full-pass kernel field-for-field in TestTiledLog).
CFG5_TILED = SimConfig(n=5, log_len=512, window=8, apply_batch=16,
                       max_props=8, keep=4, election_tick=10, seed=77,
                       log_chunk=128)


@pytest.mark.parametrize("seed", range(600, 612))
def test_differential_tiled_kernel(seed):
    drop = [0.0, 0.1, 0.25][seed % 3]
    crash = [0.0, 0.05, 0.1][(seed // 3) % 3]
    assert CFG5_TILED.tiled
    run_differential(CFG5_TILED, n_ticks=90, seed=seed, drop_rate=drop,
                     crash_prob=crash)


@pytest.mark.parametrize("seed", range(620, 624))
def test_differential_tiled_leader_crash_cycles(seed):
    stats = run_differential(CFG5_TILED, n_ticks=120, seed=seed,
                             crash_leader_every=30, prop_prob=0.7)
    assert stats["max_commit"] > 0


# ---------------------------------------------------------------------------
# Mailbox-wire differential: the SAME schedules, but messages ride the
# [N, N] in-flight mailboxes (kernel.py "Device-mailbox wire") with per-edge
# latency and optional per-message jitter.  The oracle replays the identical
# send-gating/guard-drop/latency schedule (oracle._tick_mailbox).
# ---------------------------------------------------------------------------

CFG3_LAT = SimConfig(n=3, log_len=64, window=8, apply_batch=16, max_props=8,
                     keep=4, election_tick=12, seed=501, latency=1)
CFG5_LAT = SimConfig(n=5, log_len=64, window=8, apply_batch=16, max_props=8,
                     keep=4, election_tick=14, seed=502, latency=2)
CFG5_JIT = SimConfig(n=5, log_len=64, window=8, apply_batch=16, max_props=8,
                     keep=4, election_tick=16, seed=503, latency=1,
                     latency_jitter=2)
CFG7_LAT = SimConfig(n=7, log_len=64, window=8, apply_batch=16, max_props=8,
                     keep=4, election_tick=14, seed=504, latency=2,
                     latency_jitter=1)
CFG3_SYNC_BOX = SimConfig(n=3, log_len=64, window=8, apply_batch=16,
                          max_props=8, keep=4, election_tick=10, seed=505,
                          force_mailboxes=True)


@pytest.mark.parametrize("seed", range(500, 530))
def test_differential_mailbox_latency1_n3(seed):
    drop = [0.0, 0.05, 0.15][seed % 3]
    run_differential(CFG3_LAT, n_ticks=120, seed=seed, drop_rate=drop)


@pytest.mark.parametrize("seed", range(530, 560))
def test_differential_mailbox_latency2_crash_n5(seed):
    drop = [0.0, 0.1][seed % 2]
    crash = [0.0, 0.05][(seed // 2) % 2]
    run_differential(CFG5_LAT, n_ticks=120, seed=seed, drop_rate=drop,
                     crash_prob=crash)


@pytest.mark.parametrize("seed", range(560, 590))
def test_differential_mailbox_jitter_reordering_n5(seed):
    drop = [0.0, 0.1, 0.2][seed % 3]
    run_differential(CFG5_JIT, n_ticks=140, seed=seed, drop_rate=drop,
                     crash_prob=0.04)


@pytest.mark.parametrize("seed", range(590, 610))
def test_differential_mailbox_heavy_faults_n7(seed):
    run_differential(CFG7_LAT, n_ticks=100, seed=seed, drop_rate=0.15,
                     crash_prob=0.06)


@pytest.mark.parametrize("seed", range(610, 620))
def test_differential_mailbox_leader_crash_cycles(seed):
    stats = run_differential(CFG5_LAT, n_ticks=140, seed=seed,
                             crash_leader_every=35, prop_prob=0.7)
    assert stats["max_commit"] > 0


@pytest.mark.parametrize("seed", range(620, 630))
def test_differential_mailbox_partition_heal(seed):
    run_differential(CFG5_JIT, n_ticks=140, seed=seed, drop_rate=0.05,
                     partition_at=(40, 80, 2))


@pytest.mark.parametrize("seed", range(630, 640))
def test_differential_forced_mailbox_at_latency_zero(seed):
    """The mailbox machinery at latency 0 must replay the synchronous
    semantics exactly (same-tick delivery through the slots)."""
    run_differential(CFG3_SYNC_BOX, n_ticks=90, seed=seed, drop_rate=0.1,
                     crash_prob=0.05)


# ---------------------------------------------------------------------------
# PreVote differential: candidacies poll at term+1 without bumping terms
# (vendor raft.go campaignPreElection) on both wires.
# ---------------------------------------------------------------------------

CFG3_PV = SimConfig(n=3, log_len=64, window=8, apply_batch=16, max_props=8,
                    keep=4, election_tick=10, seed=701, pre_vote=True)
CFG5_PV = SimConfig(n=5, log_len=64, window=8, apply_batch=16, max_props=8,
                    keep=4, election_tick=12, seed=702, pre_vote=True)
CFG5_PV_LAT = SimConfig(n=5, log_len=64, window=8, apply_batch=16,
                        max_props=8, keep=4, election_tick=14, seed=703,
                        pre_vote=True, latency=2)
CFG7_PV_JIT = SimConfig(n=7, log_len=64, window=8, apply_batch=16,
                        max_props=8, keep=4, election_tick=16, seed=704,
                        pre_vote=True, latency=1, latency_jitter=2)


@pytest.mark.parametrize("seed", range(700, 730))
def test_differential_prevote_sync_n3(seed):
    drop = [0.0, 0.1, 0.2][seed % 3]
    run_differential(CFG3_PV, n_ticks=100, seed=seed, drop_rate=drop)


@pytest.mark.parametrize("seed", range(730, 760))
def test_differential_prevote_crash_n5(seed):
    drop = [0.0, 0.1][seed % 2]
    crash = [0.0, 0.06][(seed // 2) % 2]
    run_differential(CFG5_PV, n_ticks=110, seed=seed, drop_rate=drop,
                     crash_prob=crash)


@pytest.mark.parametrize("seed", range(760, 780))
def test_differential_prevote_partition_no_term_inflation(seed):
    """The point of PreVote: a partitioned node must NOT inflate terms.
    Partition a minority, heal, and check terms stayed flat while the
    differential held per-tick."""
    stats = run_differential(CFG5_PV, n_ticks=140, seed=seed, drop_rate=0.02,
                             partition_at=(30, 90, 1))
    # without pre_vote the cut-off node campaigns ~5x during the partition
    # and would drag max_term up with it on heal
    assert stats["max_term"] <= 4


@pytest.mark.parametrize("seed", range(780, 800))
def test_differential_prevote_mailbox_latency(seed):
    drop = [0.0, 0.1][seed % 2]
    run_differential(CFG5_PV_LAT, n_ticks=120, seed=seed, drop_rate=drop,
                     crash_prob=0.04)


@pytest.mark.parametrize("seed", range(800, 815))
def test_differential_prevote_mailbox_jitter_n7(seed):
    run_differential(CFG7_PV_JIT, n_ticks=110, seed=seed, drop_rate=0.12,
                     crash_prob=0.05)


# ---------------------------------------------------------------------------
# Leader-transfer differential: TIMEOUT_NOW forced campaigns with
# CAMPAIGN_TRANSFER lease bypass and proposal blocking mid-transfer.
# ---------------------------------------------------------------------------

@pytest.mark.parametrize("seed", range(900, 925))
def test_differential_leader_transfer_sync(seed):
    drop = [0.0, 0.1][seed % 2]
    stats = run_differential(CFG5, n_ticks=130, seed=seed, drop_rate=drop,
                             transfer_every=25, prop_prob=0.6)
    assert stats["max_commit"] > 0


@pytest.mark.parametrize("seed", range(925, 945))
def test_differential_leader_transfer_prevote(seed):
    stats = run_differential(CFG5_PV, n_ticks=130, seed=seed, drop_rate=0.05,
                             transfer_every=30, prop_prob=0.6)
    assert stats["max_commit"] > 0


@pytest.mark.parametrize("seed", range(945, 965))
def test_differential_leader_transfer_mailbox(seed):
    stats = run_differential(CFG5_LAT, n_ticks=140, seed=seed,
                             transfer_every=30, prop_prob=0.6,
                             crash_prob=0.03)
    assert stats["max_commit"] > 0


@pytest.mark.parametrize("seed", range(965, 980))
def test_differential_leader_transfer_jitter_prevote(seed):
    run_differential(CFG7_PV_JIT, n_ticks=120, seed=seed, drop_rate=0.08,
                     transfer_every=35)


# ---------------------------------------------------------------------------
# Pipelined-append differential (vendor MaxInflightMsgs): K appends ride
# each edge with optimistic next / probe-replicate transitions.
# ---------------------------------------------------------------------------

CFG3_K2 = SimConfig(n=3, log_len=64, window=8, apply_batch=16, max_props=8,
                    keep=4, election_tick=12, seed=801, latency=1,
                    inflight=2)
CFG5_K3 = SimConfig(n=5, log_len=64, window=8, apply_batch=16, max_props=8,
                    keep=4, election_tick=14, seed=802, latency=2,
                    inflight=3)
CFG5_K4_JIT = SimConfig(n=5, log_len=64, window=8, apply_batch=16,
                        max_props=8, keep=4, election_tick=18, seed=803,
                        latency=2, latency_jitter=2, inflight=4,
                        pre_vote=True)


@pytest.mark.parametrize("seed", range(820, 850))
def test_differential_pipelined_k2_n3(seed):
    drop = [0.0, 0.05, 0.15][seed % 3]
    run_differential(CFG3_K2, n_ticks=120, seed=seed, drop_rate=drop)


@pytest.mark.parametrize("seed", range(850, 880))
def test_differential_pipelined_k3_crash_n5(seed):
    drop = [0.0, 0.1][seed % 2]
    crash = [0.0, 0.05][(seed // 2) % 2]
    run_differential(CFG5_K3, n_ticks=120, seed=seed, drop_rate=drop,
                     crash_prob=crash)


@pytest.mark.parametrize("seed", range(880, 900))
def test_differential_pipelined_k4_jitter_prevote(seed):
    run_differential(CFG5_K4_JIT, n_ticks=130, seed=seed, drop_rate=0.1,
                     crash_prob=0.04)


@pytest.mark.parametrize("seed", range(900, 910))
def test_differential_pipelined_transfer(seed):
    stats = run_differential(CFG5_K3, n_ticks=140, seed=seed,
                             transfer_every=35, prop_prob=0.7)
    assert stats["max_commit"] > 0


# ---------------------------------------------------------------------------
# Wider-cluster mailbox differential: n=15 exercises quorum math, multi-way
# vote splits and fan-in aggregation at a size past the toy configs.
# ---------------------------------------------------------------------------

CFG15 = SimConfig(n=15, log_len=64, window=8, apply_batch=16, max_props=8,
                  keep=4, election_tick=20, seed=901, latency=2,
                  latency_jitter=1, inflight=2, pre_vote=True)


@pytest.mark.parametrize("seed", range(910, 925))
def test_differential_wide_cluster_mailbox(seed):
    drop = [0.0, 0.1][seed % 2]
    run_differential(CFG15, n_ticks=100, seed=seed, drop_rate=drop,
                     crash_prob=0.03)


# ---------------------------------------------------------------------------
# Membership differential: log-driven conf changes (committed CONF entries
# flipping per-row member views, kernel Phase E) under the schedules of the
# reference's membership test territory (raft_test.go:63-1025): add/remove
# churn with drops, crashes, PreVote, the mailbox wire and pipelining.
# The oracle replays every flip through core add_node/remove_node at apply
# time, so kernel-vs-core conformance now covers membership.
# ---------------------------------------------------------------------------

@pytest.mark.parametrize("seed", range(1000, 1030))
def test_differential_membership_sync_n5(seed):
    drop = [0.0, 0.05, 0.15][seed % 3]
    stats = run_differential(CFG5, n_ticks=140, seed=seed, drop_rate=drop,
                             conf_every=18, prop_prob=0.5)
    assert stats["max_commit"] > 0


@pytest.mark.parametrize("seed", range(1030, 1055))
def test_differential_membership_crash_n7(seed):
    drop = [0.0, 0.1][seed % 2]
    crash = [0.0, 0.05][(seed // 2) % 2]
    run_differential(CFG7, n_ticks=140, seed=seed, drop_rate=drop,
                     crash_prob=crash, conf_every=20, min_members=4)


@pytest.mark.parametrize("seed", range(1055, 1075))
def test_differential_membership_prevote(seed):
    run_differential(CFG5_PV, n_ticks=150, seed=seed, drop_rate=0.05,
                     conf_every=22, prop_prob=0.6)


@pytest.mark.parametrize("seed", range(1075, 1095))
def test_differential_membership_mailbox(seed):
    drop = [0.0, 0.08][seed % 2]
    run_differential(CFG5_LAT, n_ticks=160, seed=seed, drop_rate=drop,
                     conf_every=25, crash_prob=0.03)


@pytest.mark.parametrize("seed", range(1095, 1110))
def test_differential_membership_pipelined_jitter(seed):
    run_differential(CFG5_K4_JIT, n_ticks=160, seed=seed, drop_rate=0.08,
                     conf_every=28)


@pytest.mark.parametrize("seed", range(1110, 1125))
def test_differential_membership_bootstrap_grow(seed):
    """Start from a 3-voter bootstrap of 5 rows and grow via committed CONF
    adds (the joiner catch-up path incl. snapshots carrying the config)."""
    stats = run_differential(CFG5, n_ticks=160, seed=seed, drop_rate=0.05,
                             conf_every=15, voters=range(3), prop_prob=0.7,
                             min_members=3)
    assert stats["max_commit"] > 0


@pytest.mark.parametrize("seed", range(1125, 1140))
def test_differential_membership_leader_crash_cycles(seed):
    """Conf churn composed with periodic leader kills — membership changes
    mid-election are the reference's hardest raft territory."""
    run_differential(CFG5, n_ticks=160, seed=seed, crash_leader_every=35,
                     conf_every=24, prop_prob=0.6)


@pytest.mark.parametrize("seed", range(1140, 1150))
def test_differential_membership_transfer(seed):
    run_differential(CFG5, n_ticks=150, seed=seed, transfer_every=40,
                     conf_every=26, prop_prob=0.6)


@pytest.mark.parametrize("seed", range(1150, 1165))
def test_differential_membership_remove_leader_sync(seed):
    """The sitting leader proposes its OWN removal (self-excluded quorums,
    ProposalDropped after apply), then the shell stops it and the
    survivors elect — swarmkit's demote-the-leader flow."""
    stats = run_differential(CFG5, n_ticks=160, seed=seed, drop_rate=0.05,
                             remove_leader_every=45, prop_prob=0.6,
                             min_members=3)
    assert stats["max_commit"] > 0


@pytest.mark.parametrize("seed", range(1165, 1175))
def test_differential_membership_remove_leader_mailbox(seed):
    run_differential(CFG5_LAT, n_ticks=170, seed=seed, drop_rate=0.05,
                     remove_leader_every=50, conf_every=27, prop_prob=0.5)


@pytest.mark.parametrize("seed", range(1175, 1185))
def test_differential_membership_remove_leader_prevote(seed):
    run_differential(CFG5_PV, n_ticks=170, seed=seed, drop_rate=0.05,
                     remove_leader_every=48, prop_prob=0.5)


# ---------------------------------------------------------------------------
# n=64 differential: the gate at a size with real multi-partition dynamics
# (VERDICT r03 weak #2 asked for the differential bar above n=15; measured
# cost is ~6-8 s/schedule, so no oracle vectorization was needed).  Covers
# both wires, faults, membership churn and pipelining at n=64.
# ---------------------------------------------------------------------------

CFG64 = SimConfig(n=64, log_len=128, window=16, apply_batch=32, max_props=16,
                  keep=8, election_tick=20, seed=6401)
CFG64_MB = SimConfig(n=64, log_len=128, window=16, apply_batch=32,
                     max_props=16, keep=8, election_tick=24, seed=6402,
                     latency=2, latency_jitter=1, inflight=2, pre_vote=True)


@pytest.mark.parametrize("seed", range(6400, 6406))
def test_differential_n64_sync(seed):
    drop = [0.0, 0.05, 0.1][seed % 3]
    crash = [0.0, 0.03][seed % 2]
    stats = run_differential(CFG64, n_ticks=100, seed=seed, drop_rate=drop,
                             crash_prob=crash, prop_prob=0.6)
    assert stats["max_commit"] > 0


@pytest.mark.parametrize("seed", range(6406, 6410))
def test_differential_n64_sync_membership(seed):
    stats = run_differential(CFG64, n_ticks=110, seed=seed, drop_rate=0.05,
                             conf_every=22, min_members=33)
    assert stats["max_commit"] > 0


@pytest.mark.parametrize("seed", range(6410, 6414))
def test_differential_n64_partition_heal(seed):
    """Multi-way split: cut a 21-row minority, heal, re-converge — the
    regime where many concurrent candidacies interact."""
    stats = run_differential(CFG64, n_ticks=120, seed=seed, drop_rate=0.02,
                             partition_at=(30, 70, 21))
    assert stats["max_commit"] > 0


@pytest.mark.parametrize("seed", range(6414, 6420))
def test_differential_n64_mailbox_pipelined(seed):
    drop = [0.0, 0.05][seed % 2]
    stats = run_differential(CFG64_MB, n_ticks=110, seed=seed, drop_rate=drop,
                             crash_prob=0.02)
    assert stats["max_commit"] > 0


# ---------------------------------------------------------------------------
# n=64 hard families (VERDICT r04 weak #3): remove-the-leader,
# leader-transfer, and snapshot-catchup at the size where multi-candidacy
# and view-divergence dynamics actually interact — on both wires.
# ---------------------------------------------------------------------------

@pytest.mark.parametrize("seed", range(6420, 6423))
def test_differential_n64_remove_leader_sync(seed):
    """The sitting leader repeatedly proposes its own removal at n=64:
    self-excluded commit quorums, survivor elections, churned views."""
    stats = run_differential(CFG64, n_ticks=130, seed=seed, drop_rate=0.03,
                             remove_leader_every=40, min_members=33,
                             prop_prob=0.5)
    assert stats["max_commit"] > 0


@pytest.mark.parametrize("seed", range(6423, 6426))
def test_differential_n64_remove_leader_mailbox(seed):
    stats = run_differential(CFG64_MB, n_ticks=130, seed=seed,
                             remove_leader_every=44, min_members=33,
                             prop_prob=0.5)
    assert stats["max_commit"] > 0


@pytest.mark.parametrize("seed", range(6426, 6429))
def test_differential_n64_transfer_sync(seed):
    """Leader handoffs every 25 ticks at n=64 (TIMEOUT_NOW fan-in with 63
    potential interferers), with drops."""
    stats = run_differential(CFG64, n_ticks=120, seed=seed, drop_rate=0.04,
                             transfer_every=25, prop_prob=0.6)
    assert stats["max_commit"] > 0


@pytest.mark.parametrize("seed", range(6429, 6432))
def test_differential_n64_transfer_mailbox(seed):
    stats = run_differential(CFG64_MB, n_ticks=120, seed=seed,
                             transfer_every=28, prop_prob=0.6)
    assert stats["max_commit"] > 0


@pytest.mark.parametrize("seed", range(6432, 6435))
def test_differential_n64_snapshot_catchup_sync(seed):
    """One follower sleeps through the compaction window (L=128, heavy
    proposals) and must be caught up by the snapshot path, identically on
    both sides."""
    stats = run_differential(CFG64, n_ticks=120, seed=seed, prop_prob=0.9,
                             sleep_node=(5, 25, 85))
    assert stats["max_commit"] > cfg_snapshot_floor(CFG64)


@pytest.mark.parametrize("seed", range(6435, 6438))
def test_differential_n64_snapshot_catchup_mailbox(seed):
    stats = run_differential(CFG64_MB, n_ticks=130, seed=seed, prop_prob=0.9,
                             sleep_node=(5, 25, 90))
    assert stats["max_commit"] > cfg_snapshot_floor(CFG64_MB)


def cfg_snapshot_floor(cfg) -> int:
    """Commit depth guaranteeing the sleeper fell past the ring window:
    ring capacity (log_len) — if commit exceeds this while a node slept
    from early on, its catch-up HAD to go through a snapshot."""
    return cfg.log_len


def test_differential_slow_luck_schedule_eventually_commits():
    """Fresh-seed sweep find (seed 2009343, 2026-07-31): a 5-node PreVote
    mailbox schedule with crash_prob=0.04 + drop=0.08 elected through
    term 7 with ZERO commits in 220 ticks — every leader died before its
    first commit.  Kernel==oracle the whole way; the same schedule run
    longer commits hundreds of entries.  Pins both facts: no divergence
    at the short horizon, and liveness at the long one (the sweep tool's
    no-progress check now extends the horizon before calling a stall)."""
    cfg = SimConfig(n=5, log_len=64, window=8, apply_batch=16, max_props=8,
                    keep=4, election_tick=14, seed=5, latency=2,
                    latency_jitter=1, inflight=2, pre_vote=True)
    short = run_differential(cfg, seed=2009343, n_ticks=220,
                             drop_rate=0.08, crash_prob=0.04)
    assert short["max_term"] >= 5        # elections churned...
    long_ = run_differential(cfg, seed=2009343, n_ticks=600,
                             drop_rate=0.08, crash_prob=0.04)
    assert long_["max_commit"] > 100     # ...but the cluster is live

"""External CA signing, secret drivers, and named generic resources
(VERDICT r02 missing #4/#5/#7 — one acceptance test each, plus edges).
"""

import asyncio

import pytest

from swarmkit_tpu.api import (
    Annotations, ContainerSpec, Driver, NodeSpec, Secret, SecretSpec, Task,
    TaskSpec, TaskState, TaskStatus,
)
from swarmkit_tpu.api.specs import SecretReference
from swarmkit_tpu.api.objects import Node as ApiNode
from swarmkit_tpu.api.specs import (
    ExternalCA as ExternalCASpec, ResourceRequirements, Resources,
)
from swarmkit_tpu.api.types import NodeDescription, NodeResources
from swarmkit_tpu.ca.certificates import (
    MANAGER_ROLE_OU, WORKER_ROLE_OU, RootCA, create_csr, parse_identity,
)
from tests.conftest import async_test, requires_cryptography


# ---------------------------------------------------------------------------
# external CA

@async_test
@requires_cryptography
async def test_external_ca_signs_for_keyless_cluster():
    """The CA server holds NO signing key; issuance goes through the
    external-ca-example CFSSL endpoint and the result chains to the cluster
    root (reference: ca/external.go + cmd/external-ca-example)."""
    from swarmkit_tpu.ca.external import ExternalCAClient
    from swarmkit_tpu.cmd.external_ca_example import serve

    signing_root = RootCA.create()
    server, port = serve(signing_root)
    try:
        public_root = RootCA(signing_root.cert_pem)  # no key
        assert not public_root.can_sign
        client = ExternalCAClient(
            [ExternalCASpec(url=f"http://127.0.0.1:{port}")], public_root)
        csr_pem, _key = create_csr()
        issued = await client.sign(csr_pem, "node-x", WORKER_ROLE_OU,
                                   "org-1")
        node_id, role, org = parse_identity(issued.cert_pem)
        assert (node_id, role, org) == ("node-x", WORKER_ROLE_OU, "org-1")
        public_root.validate_cert_chain(issued.cert_pem)
    finally:
        server.shutdown()


@async_test
@requires_cryptography
async def test_external_ca_refusal_is_an_error():
    from swarmkit_tpu.ca.external import ExternalCAClient, ExternalCAError
    from swarmkit_tpu.cmd.external_ca_example import serve

    signing_root = RootCA.create()
    server, port = serve(signing_root)
    try:
        client = ExternalCAClient(
            [ExternalCASpec(url=f"http://127.0.0.1:{port}")],
            RootCA(signing_root.cert_pem))
        with pytest.raises((ExternalCAError, Exception)):
            await client.sign(b"not a csr", "n", WORKER_ROLE_OU, "o")
    finally:
        server.shutdown()


@async_test
@requires_cryptography
async def test_ca_server_uses_external_when_keyless():
    """CAServer._sign delegates to the cluster-spec external CA when the
    local root cannot sign (reference: server.go signNodeCert path)."""
    from swarmkit_tpu.api.objects import Cluster
    from swarmkit_tpu.api.specs import CAConfig, ClusterSpec
    from swarmkit_tpu.ca.config import generate_join_token
    from swarmkit_tpu.ca.server import CAServer
    from swarmkit_tpu.cmd.external_ca_example import serve
    from swarmkit_tpu.store.memory import MemoryStore

    signing_root = RootCA.create()
    server, port = serve(signing_root)
    try:
        store = MemoryStore()
        public_root = RootCA(signing_root.cert_pem)
        token = generate_join_token(public_root)
        cluster = Cluster(
            id="c1",
            spec=ClusterSpec(
                annotations=Annotations(name="default"),
                ca_config=CAConfig(external_cas=[
                    ExternalCASpec(url=f"http://127.0.0.1:{port}")])))
        cluster.root_ca.join_token_worker = token
        cluster.root_ca.join_token_manager = generate_join_token(public_root)
        await store.update(lambda tx: tx.create(cluster))

        ca = CAServer(store, public_root, org="org-e")
        csr_pem, _ = create_csr()
        node_id, issued = await ca.issue_node_certificate(
            csr_pem, token, requested_node_id="w-ext")
        assert node_id == "w-ext"
        _, role, org = parse_identity(issued.cert_pem)
        assert role == WORKER_ROLE_OU and org == "org-e"
        public_root.validate_cert_chain(issued.cert_pem)
    finally:
        server.shutdown()


# ---------------------------------------------------------------------------
# secret drivers

def _secret_task(tid="t1"):
    return Task(id=tid, service_id="s1",
                spec=TaskSpec(container=ContainerSpec(
                    image="img",
                    secrets=[SecretReference(secret_id="sec1",
                                             secret_name="api-key")])),
                status=TaskStatus(state=TaskState.ASSIGNED),
                desired_state=TaskState.RUNNING)


@async_test
async def test_secret_driver_resolves_value_at_assignment():
    """A driver-backed secret's value comes from the provider at assignment
    time and never rests in the store (reference: drivers/provider.go +
    dispatcher/assignments.go:294-316)."""
    from swarmkit_tpu.manager.dispatcher.assignments import AssignmentSet
    from swarmkit_tpu.manager.drivers import DriverProvider
    from swarmkit_tpu.store.memory import MemoryStore

    store = MemoryStore()
    await store.update(lambda tx: tx.create(Secret(
        id="sec1", spec=SecretSpec(annotations=Annotations(name="api-key"),
                                   driver=Driver(name="vault")))))

    calls = []

    class VaultDriver:
        def get(self, spec, task):
            calls.append((spec.annotations.name, task.id))
            return f"value-for-{task.id}".encode()

    provider = DriverProvider()
    provider.register_secret_driver("vault", VaultDriver())

    aset = AssignmentSet("node-1", drivers=provider)
    store.view(lambda tx: aset.add_or_update_task(tx, _secret_task()))
    msg = aset.message()
    secrets = [c.assignment.secret for c in msg.changes
               if c.assignment.secret is not None]
    assert secrets and secrets[0].spec.data == b"value-for-t1"
    assert calls == [("api-key", "t1")]
    # the stored object still has no payload
    assert store.get("secret", "sec1").spec.data == b""


@async_test
async def test_secret_driver_missing_provider_skips_secret():
    from swarmkit_tpu.manager.dispatcher.assignments import AssignmentSet
    from swarmkit_tpu.store.memory import MemoryStore

    store = MemoryStore()
    await store.update(lambda tx: tx.create(Secret(
        id="sec1", spec=SecretSpec(annotations=Annotations(name="api-key"),
                                   driver=Driver(name="vault")))))
    aset = AssignmentSet("node-1", drivers=None)
    store.view(lambda tx: aset.add_or_update_task(tx, _secret_task()))
    msg = aset.message()
    # the task still flows; the unresolvable secret is withheld
    kinds = [("task" if c.assignment.task else "secret")
             for c in msg.changes]
    assert "task" in kinds and "secret" not in kinds


# ---------------------------------------------------------------------------
# named generic resources

def _node_with_chips(node_id="n1", ids=("0", "1", "2", "3")):
    from swarmkit_tpu.api import NodeState
    from swarmkit_tpu.api.objects import NodeStatus

    return ApiNode(
        id=node_id,
        spec=NodeSpec(annotations=Annotations(name=node_id)),
        status=NodeStatus(state=NodeState.READY),
        description=NodeDescription(
            hostname=node_id,
            resources=NodeResources(
                generic={"tpu-chip": len(ids)},
                generic_named={"tpu-chip": list(ids)})))


def _chip_task(tid, n):
    return Task(id=tid, service_id="s1",
                spec=TaskSpec(
                    container=ContainerSpec(image="tpu://matmul"),
                    resources=ResourceRequirements(
                        reservations=Resources(generic={"tpu-chip": n}))),
                status=TaskStatus(state=TaskState.PENDING),
                desired_state=TaskState.RUNNING)


def test_named_resources_claimed_disjoint_and_released():
    """Named string resources: the scheduler view claims SPECIFIC ids per
    task, never double-books, refuses when exhausted, and releases on task
    removal (reference: api/genericresource + scheduler/filter.go:107-150)."""
    from swarmkit_tpu.manager.scheduler.filters import ResourceFilter
    from swarmkit_tpu.manager.scheduler.nodeinfo import NodeInfo

    info = NodeInfo(_node_with_chips())
    f = ResourceFilter()

    t1, t2, t3 = _chip_task("t1", 2), _chip_task("t2", 2), _chip_task("t3", 1)

    assert f.set_task(t1) and f.check(info)
    t1.assigned_generic = info.claim_named({"tpu-chip": 2})
    assert t1.assigned_generic == {"tpu-chip": ["0", "1"]}
    info.add_task(t1)

    assert f.set_task(t2) and f.check(info)
    t2.assigned_generic = info.claim_named({"tpu-chip": 2})
    assert t2.assigned_generic == {"tpu-chip": ["2", "3"]}
    info.add_task(t2)

    # exhausted: the filter refuses before any claim happens
    assert f.set_task(t3) and not f.check(info)
    assert info.claim_named({"tpu-chip": 1}) == {}

    # release: removing t1 frees exactly its ids
    info.remove_task(t1)
    assert f.check(info)
    assert info.claim_named({"tpu-chip": 1}) == {"tpu-chip": ["0"]}


@async_test
async def test_scheduler_assigns_named_ids_end_to_end():
    """Through the real scheduler: tasks land with disjoint concrete chip
    ids recorded on Task.assigned_generic."""
    from swarmkit_tpu.manager.scheduler.scheduler import Scheduler
    from swarmkit_tpu.store.memory import MemoryStore
    from swarmkit_tpu.utils.clock import FakeClock

    clock = FakeClock()
    store = MemoryStore(clock=clock.now)
    sched = Scheduler(store, clock=clock)
    await sched.start()
    # created AFTER start: the scheduler is event-driven (leader-only loop
    # starts before the objects it watches appear)
    await store.update(lambda tx: tx.create(_node_with_chips()))
    for tid in ("t1", "t2"):
        await store.update(
            lambda tx, tid=tid: tx.create(_chip_task(tid, 2)))
    try:
        for _ in range(40):
            for _ in range(8):
                await asyncio.sleep(0)
            await clock.advance(1.0)
            for _ in range(8):
                await asyncio.sleep(0)
            tasks = store.find("task")
            if all(t.status.state == TaskState.ASSIGNED for t in tasks):
                break
        tasks = {t.id: t for t in store.find("task")}
        assert all(t.status.state == TaskState.ASSIGNED
                   for t in tasks.values()), {
                       t.id: t.status.state for t in tasks.values()}
        ids1 = set(tasks["t1"].assigned_generic["tpu-chip"])
        ids2 = set(tasks["t2"].assigned_generic["tpu-chip"])
        assert len(ids1) == 2 and len(ids2) == 2
        assert not (ids1 & ids2), "chip ids double-booked"
    finally:
        await sched.stop()

"""Flight-recorder tests: ring semantics, decode, capture, export, the
recorder-off bit-identity guarantee, and the DST post-mortem flow.

The load-bearing guarantees:

- ``record_events=False`` (the default) must leave the kernel program
  untouched — every non-recorder SimState field bit-identical to a run
  that never knew the recorder existed (the recording block is gated in
  Python, so it is simply not traced).
- A seed-pinned DST violation re-run with recording on must end with
  events that explain the violated invariant.
- Exported traces must be valid Chrome/Perfetto JSON.
"""

import dataclasses
import json

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from swarmkit_tpu.flightrec import (
    APPEND_REJECT, COMMIT_ADVANCE, ELECTION_WON, EVENT_WIDTH, TERM_BUMP,
    FlightEvent, FlightRecord, capture, decode_rings, decode_state,
    diff_records, load_record, ring_append, save_record, summarize,
    to_chrome_trace, validate_chrome_trace,
)
from swarmkit_tpu.raft.sim.run import run_ticks
from swarmkit_tpu.raft.sim.state import SimConfig, SimState, init_state

I32 = jnp.int32


def small_cfg(**kw):
    base = dict(n=5, log_len=64, window=8, apply_batch=16, max_props=8,
                keep=4, election_tick=10, seed=3)
    base.update(kw)
    return SimConfig(**base)


# ---------------------------------------------------------------------------
# ring primitives


def test_ring_append_masked_rows_only():
    buf = jnp.zeros((3, 4, EVENT_WIDTH), I32)
    pos = jnp.zeros((3,), I32)
    mask = jnp.asarray([True, False, True])
    buf, pos = ring_append(buf, pos, mask, jnp.asarray(7, I32), ELECTION_WON,
                           jnp.asarray([1, 2, 3], I32),
                           jnp.asarray([4, 5, 6], I32))
    assert pos.tolist() == [1, 0, 1]
    assert buf[0, 0].tolist() == [7, ELECTION_WON, 1, 4]
    assert buf[1, 0].tolist() == [0, 0, 0, 0]   # masked-out row untouched
    assert buf[2, 0].tolist() == [7, ELECTION_WON, 3, 6]


def test_ring_wraps_and_reports_dropped():
    cap_slots = 4
    buf = jnp.zeros((2, cap_slots, EVENT_WIDTH), I32)
    pos = jnp.zeros((2,), I32)
    mask = jnp.asarray([True, True])
    for t in range(6):   # 6 appends into a 4-slot ring: 2 dropped
        buf, pos = ring_append(buf, pos, mask, jnp.asarray(t, I32),
                               COMMIT_ADVANCE,
                               jnp.full((2,), t, I32), jnp.zeros((2,), I32))
    events, dropped = decode_rings(buf, pos)
    assert dropped.tolist() == [2, 2]
    # oldest surviving event is t=2 — 0 and 1 were overwritten
    ticks = sorted({e.tick for e in events})
    assert ticks == [2, 3, 4, 5]


def test_decode_orders_by_tick_node_seq():
    buf = jnp.zeros((2, 8, EVENT_WIDTH), I32)
    pos = jnp.zeros((2,), I32)
    both = jnp.asarray([True, True])
    only1 = jnp.asarray([False, True])
    buf, pos = ring_append(buf, pos, both, jnp.asarray(5, I32), TERM_BUMP,
                           jnp.zeros((2,), I32), jnp.zeros((2,), I32))
    buf, pos = ring_append(buf, pos, only1, jnp.asarray(5, I32), ELECTION_WON,
                           jnp.zeros((2,), I32), jnp.zeros((2,), I32))
    buf, pos = ring_append(buf, pos, both, jnp.asarray(9, I32),
                           COMMIT_ADVANCE,
                           jnp.zeros((2,), I32), jnp.zeros((2,), I32))
    events, _ = decode_rings(buf, pos)
    keys = [(e.tick, e.node, e.seq) for e in events]
    assert keys == sorted(keys)
    # within node 1 at tick 5, TERM_BUMP precedes ELECTION_WON (append order)
    n1t5 = [e.name for e in events if e.node == 1 and e.tick == 5]
    assert n1t5 == ["TERM_BUMP", "ELECTION_WON"]


def test_decode_state_requires_recording():
    cfg = small_cfg()
    with pytest.raises(ValueError, match="record_events"):
        decode_state(init_state(cfg))


def test_event_ring_validated():
    with pytest.raises(ValueError, match="event_ring"):
        small_cfg(record_events=True, event_ring=4)


# ---------------------------------------------------------------------------
# recorded runs


def recorded_run(ticks=40, **kw):
    cfg = small_cfg(record_events=True, event_ring=128, **kw)
    final, _ = run_ticks(init_state(cfg), cfg, ticks, prop_count=1)
    return cfg, final


def test_recorded_run_produces_election_and_commit_events():
    _, final = recorded_run()
    events, dropped = decode_state(final)
    names = {e.name for e in events}
    assert "ELECTION_WON" in names
    assert "TERM_BUMP" in names
    assert "COMMIT_ADVANCE" in names
    assert all(d == 0 for d in dropped)   # 128-slot ring, 40 ticks: no wrap
    # commit deltas are positive and commit values non-decreasing per node
    for node in range(final.commit.shape[0]):
        commits = [e.arg0 for e in events
                   if e.node == node and e.code == COMMIT_ADVANCE]
        assert commits == sorted(commits)


def test_recorder_off_is_bit_identical():
    """The acceptance regression: with record_events=False every kernel
    output matches a run of the identical config with recording on —
    recording only ADDS the ev_* fields, it never perturbs the sim."""
    cfg_off = small_cfg()
    cfg_on = small_cfg(record_events=True, event_ring=64)
    off, _ = run_ticks(init_state(cfg_off), cfg_off, 50, prop_count=1)
    on, _ = run_ticks(init_state(cfg_on), cfg_on, 50, prop_count=1)
    assert off.ev_buf is None and on.ev_buf is not None
    for f in dataclasses.fields(SimState):
        if f.name.startswith("ev_"):
            continue
        a, b = getattr(off, f.name), getattr(on, f.name)
        if a is None:
            assert b is None, f.name
            continue
        assert np.array_equal(np.asarray(a), np.asarray(b)), \
            f"field {f.name} diverged with recording on"


def test_recording_composes_with_vmap():
    cfg = small_cfg(record_events=True, event_ring=32)
    from swarmkit_tpu.raft.sim.kernel import step

    batched = jax.tree_util.tree_map(
        lambda a: jnp.broadcast_to(a, (3,) + a.shape), init_state(cfg))
    stepped = jax.vmap(lambda s: step(s, cfg))(batched)
    assert stepped.ev_buf.shape == (3, cfg.n, 32, EVENT_WIDTH)
    assert stepped.ev_pos.shape == (3, cfg.n)


# ---------------------------------------------------------------------------
# capture / save / load / summarize / diff


def test_capture_record_roundtrip(tmp_path):
    from swarmkit_tpu.metrics.registry import MetricsRegistry

    _, final = recorded_run()
    obs = MetricsRegistry()
    rec = capture(final, trigger="manual", meta={"k": "v"}, obs=obs)
    assert rec.n == 5 and rec.events and rec.meta == {"k": "v"}
    snap = obs.snapshot()
    assert snap["swarm_flightrec_captures_total"]["trigger=manual"] == 1.0
    assert sum(snap["swarm_flightrec_events_total"].values()) == \
        len(rec.events)

    path = tmp_path / "rec.json"
    save_record(rec, str(path))
    back = load_record(str(path))
    assert [e.to_dict() for e in back.events] == \
        [e.to_dict() for e in rec.events]
    assert back.trigger == "manual" and back.meta == {"k": "v"}

    text = summarize(back, last=5)
    assert "trigger=manual" in text and "COMMIT_ADVANCE" in text


def test_load_record_rejects_unknown_version(tmp_path):
    p = tmp_path / "bad.json"
    p.write_text(json.dumps({"version": 99, "events": []}))
    with pytest.raises(ValueError, match="version"):
        load_record(str(p))


def test_diff_records_localizes_first_divergence():
    e = lambda tick, code, a0: FlightEvent(tick=tick, node=0, code=code,
                                           arg0=a0, arg1=0, seq=0)
    a = FlightRecord(events=[e(1, TERM_BUMP, 1), e(2, COMMIT_ADVANCE, 3)],
                     dropped=[0], n=1)
    b = FlightRecord(events=[e(1, TERM_BUMP, 1), e(4, COMMIT_ADVANCE, 3)],
                     dropped=[0], n=1)
    out = diff_records(a, b)
    assert "first divergence at event #1" in out
    assert diff_records(a, a).endswith("streams are identical")


# ---------------------------------------------------------------------------
# Chrome-trace export


def test_chrome_trace_schema_valid():
    _, final = recorded_run()
    events, _ = decode_state(final)
    spans = [{"name": "raft.propose", "span_id": "s1", "parent_id": None,
              "start": 10.0, "duration": 0.25, "attrs": {"node": "m1"}},
             {"name": "dispatcher.session", "span_id": "s2",
              "parent_id": "s1", "start": 10.1, "duration": 0.05,
              "attrs": {}}]
    trace = to_chrome_trace(events, spans)
    assert validate_chrome_trace(trace) == []
    json.loads(json.dumps(trace))   # round-trips as plain JSON

    te = trace["traceEvents"]
    instants = [t for t in te if t["ph"] == "i"]
    completes = [t for t in te if t["ph"] == "X"]
    assert len(instants) == len(events)
    assert len(completes) == len(spans)
    # one sim track per node, one host track per subsystem
    assert {t["pid"] for t in instants} == {1}
    assert {t["pid"] for t in completes} == {2}
    host_threads = {t["args"]["name"] for t in te
                    if t["ph"] == "M" and t["pid"] == 2
                    and t["name"] == "thread_name"}
    assert host_threads == {"raft", "dispatcher"}
    sim_threads = {t["args"]["name"] for t in te
                   if t["ph"] == "M" and t["pid"] == 1
                   and t["name"] == "thread_name"}
    assert sim_threads == {f"manager {i}" for i in range(5)}


def test_validate_chrome_trace_flags_problems():
    assert validate_chrome_trace({"traceEvents": "nope"})
    assert validate_chrome_trace(
        {"traceEvents": [{"ph": "i", "pid": 1}]})        # missing keys
    assert validate_chrome_trace(
        {"traceEvents": [{"ph": "?", "pid": 1, "tid": 0, "name": "x"}]})


# ---------------------------------------------------------------------------
# DST post-mortem (the acceptance scenario, seed-pinned)


def test_dst_postmortem_explains_commit_no_quorum():
    """A seed-pinned commit_no_quorum violation, re-run with recording on,
    must end with the events that explain leader_completeness: a fault
    edge / term bump / new election exposing the un-quorumed commit."""
    from swarmkit_tpu import dst

    cfg = small_cfg(seed=0)
    sched, names = dst.make_batch(cfg, schedules=24, ticks=100, seed=0)
    res = dst.explore(init_state(cfg), cfg, sched, names, prop_count=2,
                      mutation="commit_no_quorum", shard=False)
    assert len(res.violating) > 0, "seed-pinned mutation not caught"

    pm = dst.postmortem(res, cfg, sched, prop_count=2,
                        mutation="commit_no_quorum", window=20, limit=1)
    (idx, cap), = pm.items()
    assert cap["violations"], cap
    assert cap["window"], "post-mortem produced no events"
    # the re-run stopped at the violation: window ends at/near first_tick
    last_tick = cap["window"][-1]["tick"]
    assert abs(last_tick - cap["first_tick"]) <= 2
    tail_names = {e["name"] for e in cap["window"]}
    assert tail_names & {"ELECTION_WON", "TERM_BUMP", "FAULT_EDGE"}, \
        f"window does not explain the violation: {tail_names}"

    # the window rides along in the repro artifact
    art = dst.to_artifact(cfg, sched.slice(int(idx)), seed=0,
                          profile=names[int(idx)], index=int(idx),
                          prop_count=2, mutation="commit_no_quorum",
                          viol=int(res.viol[int(idx)]),
                          first_tick=int(res.first_tick[int(idx)]),
                          flight=cap)
    art = json.loads(json.dumps(art))   # artifact stays plain JSON
    assert art["flight"]["window"] == cap["window"]

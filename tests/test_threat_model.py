"""The arXiv:2601.00273 Byzantine-ish attack suite (ISSUE 15).

Fast tier: attack-registry coherence (profiles <-> schedule leaves <->
flightrec signature codes <-> metrics catalog wiring), generator
determinism, optional-leaf promotion in mixed batches, the unit semantics
of each apply verb (including the documented composition order), the
cooldown / inflight-cap defense boundaries, the SLO bit arithmetic, the
flight-recorder signatures, the forced-equivocation ElectionSafety trip
with its vote-guard counterpart, and the defense-transparency regression
(defense knobs that never bind leave every pre-existing state field
bit-identical on both kernel wires).

Slow tier: the seed-pinned catch -> shrink -> artifact -> replay attack
sweeps live in tests/test_dst_sweep.py and tests/test_fault_sweep.py.
"""

import dataclasses

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from swarmkit_tpu import dst
from swarmkit_tpu.dst.schedule import _OPTIONAL_LEAVES
from swarmkit_tpu.flightrec import codes as fcodes
from swarmkit_tpu.flightrec import decode_rings
from swarmkit_tpu.raft.sim.kernel import (
    propose, step, transfer_leadership,
)
from swarmkit_tpu.raft.sim.state import (
    LEADER, NONE, SimConfig, SimState, init_state,
)

CFG5 = SimConfig(n=5, log_len=64, window=8, apply_batch=16, max_props=8,
                 keep=4, election_tick=10, seed=0)
CFG3 = SimConfig(n=3, log_len=64, window=8, apply_batch=16, max_props=8,
                 keep=4, election_tick=10, seed=7)

# the validated equivocation scenario (tools/fault_sweep.py
# ATTACK_SCENARIOS): check_quorum off on BOTH sides — the CheckQuorum
# lease refuses vote re-requests for the unrelated reason of fresh leader
# contact, masking exactly the persisted-vote hole the profile exposes
EQ_OFF = dataclasses.replace(CFG5, check_quorum=False)
EQ_ON = dataclasses.replace(EQ_OFF, vote_guard=True)

# every defense knob on, tuned so none can BIND in a stock run: the
# uncommitted tail is bounded by the propose room check at
# log_len - max_props = 56 < 63, and the single scripted transfer below
# is never repeated inside the cooldown window
DEFENDED = dataclasses.replace(CFG5, vote_guard=True, prop_inflight_cap=63,
                               transfer_cooldown_ticks=15)

TRUE5 = jnp.ones((5,), bool)
step_j = jax.jit(step, static_argnames=("cfg",))
propose_j = jax.jit(propose, static_argnames=("cfg",))


def _arr(base, **updates):
    """dataclasses.replace with each update applied via .at[idx].set."""
    fields = {}
    for name, pairs in updates.items():
        a = getattr(base, name)
        for idx, val in pairs:
            a = a.at[idx].set(val)
        fields[name] = a
    return dataclasses.replace(base, **fields)


def _leader0(cfg=CFG5, **kw):
    """Init state with row 0 acting as leader at term 1."""
    updates = {"role": [(0, LEADER)], "term": [(0, 1)]}
    for name, pairs in kw.items():
        updates[name] = updates.get(name, []) + pairs
    return _arr(init_state(cfg), **updates)


# ---------------------------------------------------------------------------
# registry coherence: profiles <-> leaves <-> signature codes


def test_attack_profiles_are_extra_profiles():
    assert set(dst.ATTACK_PROFILES) <= set(dst.EXTRA_PROFILES)
    assert not set(dst.ATTACK_PROFILES) & set(dst.PROFILES)
    assert set(dst.ATTACK_LEAVES) == set(dst.ATTACK_PROFILES)
    assert set(dst.ATTACK_SIGNATURE_CODES) == set(dst.ATTACK_PROFILES)


def test_attack_leaves_are_optional_schedule_fields():
    fields = {f.name for f in dataclasses.fields(dst.FaultSchedule)}
    for leaf in dst.ATTACK_LEAVES.values():
        assert leaf in fields
        assert leaf in _OPTIONAL_LEAVES


def test_attack_signature_codes_resolve_in_flightrec():
    for code_name in dst.ATTACK_SIGNATURE_CODES.values():
        code = getattr(fcodes, code_name)
        assert fcodes.CODE_NAMES[code] == code_name


def test_unknown_profile_error_lists_all_grown_profiles():
    with pytest.raises(KeyError) as ei:
        dst.make_schedule(CFG3, ticks=8, profile="nope", seed=0)
    msg = str(ei.value)
    for name in dst.PROFILES + dst.EXTRA_PROFILES:
        assert name in msg
    for name in dst.ATTACK_PROFILES:   # the grown suite, explicitly
        assert name in msg


# ---------------------------------------------------------------------------
# generators: determinism and optional-leaf promotion


@pytest.mark.parametrize("profile", dst.ATTACK_PROFILES)
def test_attack_generator_deterministic_per_seed(profile):
    a = dst.make_schedule(CFG3, ticks=24, profile=profile, seed=5)
    b = dst.make_schedule(CFG3, ticks=24, profile=profile, seed=5)
    c = dst.make_schedule(CFG3, ticks=24, profile=profile, seed=6)
    la, lb = jax.tree_util.tree_leaves(a), jax.tree_util.tree_leaves(b)
    assert len(la) == len(lb)
    assert all(np.array_equal(x, y) for x, y in zip(la, lb))
    lc = jax.tree_util.tree_leaves(c)
    assert any(not np.array_equal(x, y) for x, y in zip(la, lc))
    # the profile's own action leaf is present and (seed-pinned) fires
    leaf = getattr(a, dst.ATTACK_LEAVES[profile])
    assert leaf is not None and bool(leaf.any())


def test_make_batch_promotes_optional_leaves_to_false():
    profiles = ("random_drop", "vote_equivocation", "append_flood")
    batch, names = dst.make_batch(CFG3, ticks=24, schedules=6, seed=0,
                                  profiles=profiles)
    assert names == list(profiles) * 2
    # promotion is PER LEAF: only leaves some schedule in the batch
    # carries are promoted (to all-False on the indices lacking them);
    # leaves no profile drives stay None so old artifacts keep tracing
    # the exact pre-extension program
    carried = {"rejoin_campaign", "vote_equivocate", "append_flood"}
    for leaf, shape in _OPTIONAL_LEAVES.items():
        arr = getattr(batch, leaf)
        if leaf not in carried:
            assert arr is None, leaf
            continue
        dims = (6, 24) if shape == "T" else (6, 24, CFG3.n)
        assert arr.shape == dims
    # the attack-less indices carry all-False gates, the attack indices
    # actually fire their own leaf
    for s in (0, 3):                                   # random_drop
        for leaf in carried:
            assert not bool(getattr(batch, leaf)[s].any())
    for s in (1, 4):                                   # vote_equivocation
        assert bool(batch.vote_equivocate[s].any())
    for s in (2, 5):                                   # append_flood
        assert bool(batch.append_flood[s].any())
    # slice round-trips the promoted structure
    one = batch.slice(2)
    assert one.append_flood.shape == (24,)


# ---------------------------------------------------------------------------
# apply-verb unit semantics (pre-step transforms on hand-built states)


def test_rejoin_campaign_forces_timer_on_live_followers_only():
    st = _leader0(elapsed=[(0, 3), (1, 2), (3, 2)])
    mask = jnp.array([True, True, False, True, False])
    alive = TRUE5.at[3].set(False)
    out = dst.apply_rejoin_campaign(st, mask, alive)
    assert int(out.elapsed[1]) == int(st.timeout[1])   # flagged follower
    assert int(out.elapsed[0]) == 3                    # leader exempt
    assert int(out.elapsed[3]) == 2                    # crashed exempt
    assert int(out.elapsed[2]) == 0                    # unflagged


def test_vote_equivocation_wipes_vote_but_not_guard():
    st = _arr(init_state(EQ_ON), vote=[(1, 0), (2, 4)],
              vg_vote=[(1, 0), (2, 4)], vg_term=[(1, 3), (2, 3)],
              term=[(1, 3), (2, 3)])
    mask = jnp.array([False, True, True, False, False])
    alive = TRUE5.at[2].set(False)
    out = dst.apply_vote_equivocation(st, mask, alive)
    assert int(out.vote[1]) == NONE                    # wiped
    assert int(out.vote[2]) == 4                       # crashed exempt
    # the WAL-shadow registers are deliberately out of the verb's reach:
    # with cfg.vote_guard on the dual grant stays unrepresentable
    assert int(out.vg_vote[1]) == 0
    assert int(out.vg_term[1]) == 3


def test_append_flood_stuffs_leader_and_respects_cap():
    st = _leader0()
    out = dst.apply_append_flood(st, CFG5, jnp.asarray(True), TRUE5)
    assert int(out.last[0]) == CFG5.max_props          # leader flooded
    assert not out.last[1:].any()                      # followers refuse
    idle = dst.apply_append_flood(st, CFG5, jnp.asarray(False), TRUE5)
    assert not idle.last.any()                         # gate off = no-op
    # inflight-cap boundary: tail == cap refuses, tail == cap - 1 still
    # accepts a full burst (the documented cap - 1 + max_props overshoot)
    cap_cfg = dataclasses.replace(CFG5, prop_inflight_cap=8)
    at_cap = _leader0(cap_cfg, last=[(0, 8)])
    out = dst.apply_append_flood(at_cap, cap_cfg, jnp.asarray(True), TRUE5)
    assert int(out.last[0]) == 8
    below = _leader0(cap_cfg, last=[(0, 7)])
    out = dst.apply_append_flood(below, cap_cfg, jnp.asarray(True), TRUE5)
    assert int(out.last[0]) == 7 + cap_cfg.max_props


def test_transfer_abuse_targets_lowest_flagged_and_consults_cooldown():
    st = _leader0(elapsed=[(0, 5)])
    mask = jnp.array([False, False, True, True, False])
    out = dst.apply_transfer_abuse(st, CFG5, mask, TRUE5)
    assert int(out.transferee[0]) == 2                 # lowest flagged
    assert int(out.elapsed[0]) == 0                    # timer reset
    assert (np.asarray(out.transferee[1:]) == NONE).all()
    # cooldown consult: a leader still cooling down refuses the request
    cool = _arr(_leader0(DEFENDED, elapsed=[(0, 5)]), tx_cool=[(0, 3)])
    out = dst.apply_transfer_abuse(cool, DEFENDED, mask, TRUE5)
    assert int(out.transferee[0]) == NONE
    assert int(out.elapsed[0]) == 5
    ready = _leader0(DEFENDED)
    out = dst.apply_transfer_abuse(ready, DEFENDED, mask, TRUE5)
    assert int(out.transferee[0]) == 2                 # cooldown expired


def test_transfer_leadership_cooldown_boundary():
    # the host-side request path consults the same register: 1 remaining
    # tick still refuses, 0 accepts, and a cooldown-free config ignores it
    cooling = _arr(_leader0(DEFENDED), tx_cool=[(0, 1)])
    out = transfer_leadership(cooling, DEFENDED, 0, 2)
    assert int(out.transferee[0]) == NONE
    ready = _leader0(DEFENDED)
    out = transfer_leadership(ready, DEFENDED, 0, 2)
    assert int(out.transferee[0]) == 2
    stock = _leader0(CFG5)
    out = transfer_leadership(stock, CFG5, 0, 2)
    assert int(out.transferee[0]) == 2


def test_propose_inflight_cap_boundary():
    cap_cfg = dataclasses.replace(CFG5, prop_inflight_cap=8)
    payloads = jnp.arange(CFG5.max_props, dtype=jnp.uint32)
    at_cap = _leader0(cap_cfg, last=[(0, 8)])
    out = propose(at_cap, cap_cfg, payloads, 2)
    assert int(out.last[0]) == 8                       # refused at cap
    below = _leader0(cap_cfg, last=[(0, 7)])
    out = propose(below, cap_cfg, payloads, 2)
    assert int(out.last[0]) == 9                       # cap-1 accepts
    stock = _leader0(CFG5, last=[(0, 20)])
    out = propose(stock, CFG5, payloads, 2)
    assert int(out.last[0]) == 22                      # cap off: room only


# ---------------------------------------------------------------------------
# composition: the documented fixed verb order, two attacks in one tick


def test_attack_verbs_compose_on_disjoint_rows():
    # rejoin on row 3, equivocation on row 1, flood on leader row 0 —
    # applied in the explore/repro order, every effect lands
    st = _leader0(vote=[(1, 0)], term=[(1, 1)])
    r3 = jnp.arange(5) == 3
    r1 = jnp.arange(5) == 1
    out = dst.apply_rejoin_campaign(st, r3, TRUE5)
    out = dst.apply_vote_equivocation(out, r1, TRUE5)
    out = dst.apply_append_flood(out, CFG5, jnp.asarray(True), TRUE5)
    assert int(out.elapsed[3]) == int(st.timeout[3])
    assert int(out.vote[1]) == NONE
    assert int(out.last[0]) == CFG5.max_props


def test_transfer_before_flood_blocks_the_flood():
    # the fixed order runs transfer_abuse BEFORE append_flood so a
    # transfer it starts blocks the flood's proposals on that leader —
    # the same ProposalDropped a real client sees mid-transfer
    st = _leader0()
    mask = jnp.arange(5) == 2
    out = dst.apply_transfer_abuse(st, CFG5, mask, TRUE5)
    out = dst.apply_append_flood(out, CFG5, jnp.asarray(True), TRUE5)
    assert int(out.transferee[0]) == 2
    assert int(out.last[0]) == 0                       # flood refused
    # flood alone (no transfer in flight) lands on the same state
    alone = dst.apply_append_flood(st, CFG5, jnp.asarray(True), TRUE5)
    assert int(alone.last[0]) == CFG5.max_props


# ---------------------------------------------------------------------------
# SLO defense-cost bits: strict-inequality boundaries


def test_slo_leader_churn_boundary():
    cfg = dataclasses.replace(CFG5, collect_telemetry=True,
                              slo_leader_changes=3)
    at_bound = _arr(init_state(cfg), tel_elect_hist=[(0, 3)])
    assert int(dst.check_state(at_bound, cfg)) == 0
    over = _arr(init_state(cfg), tel_elect_hist=[(0, 3), (1, 1)])
    assert int(dst.check_state(over, cfg)) == dst.SLO_LEADER_CHURN
    # bound unset = oracle off even over the line
    assert int(dst.check_state(over, dataclasses.replace(
        cfg, slo_leader_changes=0))) == 0


def test_slo_log_occupancy_boundary():
    # the bound is on the UNCOMMITTED tail max(last - commit) — the
    # quantity prop_inflight_cap gates acceptance on — not on ring
    # occupancy, which lazy compaction legitimately lets grow
    cfg = dataclasses.replace(CFG5, slo_log_occupancy=6)
    at_bound = _arr(init_state(cfg), last=[(0, 6)])
    assert int(dst.check_state(at_bound, cfg)) == 0
    over = _arr(init_state(cfg), last=[(0, 7)])
    assert int(dst.check_state(over, cfg)) == dst.SLO_LOG_OCCUPANCY
    committed = _arr(init_state(cfg), last=[(0, 10)], commit=[(0, 4)])
    assert int(dst.check_state(committed, cfg)) == 0   # tail 6 == bound


# ---------------------------------------------------------------------------
# flight-recorder signatures


def test_attack_verbs_emit_signature_events():
    cfg = dataclasses.replace(CFG5, record_events=True)
    st = _leader0(cfg, vote=[(1, 0)], term=[(1, 1)])
    out = dst.apply_rejoin_campaign(st, jnp.arange(5) == 3, TRUE5)
    out = dst.apply_vote_equivocation(out, jnp.arange(5) == 1, TRUE5)
    out = dst.apply_transfer_abuse(out, cfg, jnp.arange(5) == 2, TRUE5)
    out = dst.apply_append_flood(out, cfg, jnp.asarray(True), TRUE5)
    events, dropped = decode_rings(out.ev_buf, out.ev_pos)
    assert int(dropped.sum()) == 0
    names = {e.name for e in events}
    for code_name in dst.ATTACK_SIGNATURE_CODES.values():
        assert code_name in names
    for e in events:
        text = e.describe()
        assert isinstance(text, str) and text


def test_attack_verbs_are_noops_on_recorder_off_states():
    # without an event ring the verbs never touch ev_buf/ev_pos, so a
    # recorder-off replay traces the exact recorded program
    st = _leader0(CFG5)
    out = dst.apply_rejoin_campaign(st, jnp.arange(5) == 3, TRUE5)
    out = dst.apply_transfer_abuse(out, CFG5, jnp.arange(5) == 2, TRUE5)
    assert out.ev_buf is None and out.ev_pos is None


# ---------------------------------------------------------------------------
# forced equivocation trips ElectionSafety; the vote guard closes it


def test_equivocation_trips_election_safety_and_guard_closes_it():
    batch, names = dst.make_batch(EQ_OFF, ticks=40, schedules=8, seed=7,
                                  profiles=("vote_equivocation",))
    r_off = dst.explore(init_state(EQ_OFF), EQ_OFF, batch, profiles=names,
                        prop_count=2)
    tripped = int(((r_off.viol & dst.ELECTION_SAFETY) != 0).sum())
    assert tripped > 0, [hex(int(v)) for v in r_off.viol]
    # the persisted-vote guard makes the dual grant unrepresentable:
    # the SAME schedules come back violation-free
    r_on = dst.explore(init_state(EQ_ON), EQ_ON, batch, profiles=names,
                       prop_count=2)
    assert (r_on.viol == 0).all(), [hex(int(v)) for v in r_on.viol]


# ---------------------------------------------------------------------------
# mixed-adversary batches: stacked profiles agree with solo replays


@pytest.mark.slow
def test_mixed_adversary_batch_agrees_with_solo_replay():
    # all 12 profiles (stock + extras + attacks) in ONE batch: the
    # promoted optional leaves and the fixed verb order must leave each
    # index's outcome identical to replaying that schedule alone
    profiles = dst.PROFILES + dst.EXTRA_PROFILES
    batch, names = dst.make_batch(CFG5, ticks=40, schedules=12, seed=3,
                                  profiles=profiles)
    res = dst.explore(init_state(CFG5), CFG5, batch, profiles=names,
                      prop_count=2)
    # the stock profiles stay clean even stacked next to the attacks
    # (promoted all-False gates are value-identical to absent leaves);
    # the attack indices may legitimately trip against the undefended
    # default config — what must hold is batch/solo agreement
    for s, name in enumerate(names):
        if name not in dst.ATTACK_PROFILES:
            assert int(res.viol[s]) == 0, f"{name}: {hex(int(res.viol[s]))}"
            continue
        v, f = dst.replay(CFG5, batch.slice(s), prop_count=2)
        assert (v, f) == (int(res.viol[s]), int(res.first_tick[s])), name


# ---------------------------------------------------------------------------
# defense transparency: knobs that never bind change NOTHING else


class TestDefenseTransparency:
    """Every defense register is Python-gated and consulted only at its
    own boundary; with the knobs on but never binding, all pre-existing
    state fields stay bit-identical to the stock kernel, tick for tick.
    (The knobs-off direction is structural: an off knob never traces.)"""

    # the three new registers are the only permitted divergence
    NEW_FIELDS = frozenset({"vg_vote", "vg_term", "tx_cool"})

    def _drive(self, cfg, ticks=80):
        payloads = jnp.arange(cfg.max_props, dtype=jnp.uint32)
        eye = np.eye(cfg.n, dtype=bool)
        states = []
        st = init_state(cfg)
        for t in range(ticks):
            # partition row 1 during ticks 25..40 to force vote churn
            drop = np.zeros((cfg.n, cfg.n), bool)
            if 25 <= t < 40:
                drop[1, :] = True
                drop[:, 1] = True
                np.logical_and(drop, ~eye, out=drop)
            st = propose_j(st, cfg, payloads, 2)
            if t == 50:
                # one scripted handoff, never repeated inside a cooldown
                role = np.asarray(st.role)
                if (role == LEADER).any():
                    lead = int(np.argmax(role == LEADER))
                    st = transfer_leadership(st, cfg, lead,
                                             (lead + 2) % cfg.n)
            st = step_j(st, cfg, drop=jnp.asarray(drop))
            states.append(st)
        return states

    @pytest.mark.parametrize("wire", [
        "sync",
        pytest.param("mailbox", marks=pytest.mark.slow),  # compile budget
    ])
    def test_unbinding_defenses_are_bit_identical(self, wire):
        extra = {} if wire == "sync" else dict(latency=2, latency_jitter=1,
                                               inflight=2)
        base = dataclasses.replace(CFG5, **extra)
        defended = dataclasses.replace(DEFENDED, **extra)
        for a, b in zip(self._drive(base), self._drive(defended)):
            for fld in dataclasses.fields(SimState):
                if fld.name in self.NEW_FIELDS:
                    continue
                x, y = getattr(a, fld.name), getattr(b, fld.name)
                if x is None and y is None:
                    continue
                assert x is not None and y is not None, fld.name
                assert np.array_equal(np.asarray(x), np.asarray(y)), \
                    f"{wire}: {fld.name} diverged"

    def test_cooldown_register_decrements_to_zero(self):
        st = _arr(init_state(DEFENDED), tx_cool=[(0, 2)])
        st = step_j(st, DEFENDED)
        assert int(st.tx_cool[0]) == 1
        st = step_j(st, DEFENDED)
        assert int(st.tx_cool[0]) == 0
        st = step_j(st, DEFENDED)
        assert int(st.tx_cool[0]) == 0                 # floored, no wrap

"""Targeted tests retiring the oracle's documented residues.

The differential gate masks three deliberate kernel-vs-etcd wire
simplifications (oracle.py D1'(a), D1'(b), D2'), each defended in prose as
"strictly fresher than etcd".  These tests turn each argument into code:
construct the exact scenario the docstring argues about, run BOTH the
kernel (carrying the simplification) and an UNMASKED etcd-faithful replay
— `core.Raft` nodes exchanging their OWN emitted messages over a
fixed-latency wire, with every native behavior firing as vendored raft.go
does (commit-advance empty-append broadcast raft.go:478-486+bcastAppend,
heartbeat-response append trigger stepLeader MsgHeartbeatResp, PreVote
deposal on higher-term rejections Step m.Term>r.Term) — and assert the
two TRAJECTORIES CONVERGE: same leader, same term, same commit, with the
kernel's extra delay bounded by the documented cadence terms.
"""

from __future__ import annotations

import dataclasses

import jax
import jax.numpy as jnp
import numpy as np

from swarmkit_tpu.raft import core
from swarmkit_tpu.raft.messages import Entry, HardState, Message, MsgType
from swarmkit_tpu.raft.sim import SimConfig, init_state
from swarmkit_tpu.raft.sim.kernel import propose, step
from swarmkit_tpu.raft.sim.state import CANDIDATE, FOLLOWER, LEADER, NONE

_step = jax.jit(step, static_argnames=("cfg",))
_propose = jax.jit(propose, static_argnames=("cfg",))


class EtcdWire:
    """core.Raft nodes on a fixed-latency wire with NO oracle masking.

    A message sent at tick T is stepped at tick T+latency; responses
    emitted during delivery ride the next hop.  Downed nodes freeze
    (no tick, sends and receives dropped) exactly like the kernel's
    alive mask; `blocked` drops directed edges at SEND time like the
    kernel's drop matrix.
    """

    ID0 = 1   # core uses etcd's 1-based ids (NONE=0); kernel rows are
    # 0-based — the public API here is 0-based, translated via ID0.

    def __init__(self, n: int, latency: int = 1, election_tick: int = 10,
                 heartbeat_tick: int = 1, pre_vote: bool = False,
                 check_quorum: bool = True, seed: int = 0):
        self.n, self.latency = n, latency
        self.nodes: dict[int, core.Raft] = {}
        for i in range(n):
            self.nodes[i + self.ID0] = core.Raft(core.Config(
                id=i + self.ID0, peers=tuple(range(1, n + 1)),
                election_tick=election_tick,
                heartbeat_tick=heartbeat_tick, pre_vote=pre_vote,
                check_quorum=check_quorum, seed=seed + 31 * i))
        self.down: set[int] = set()         # 1-based
        self.blocked: set[tuple[int, int]] = set()   # 1-based directed
        self.inflight: list[tuple[int, Message]] = []
        self.now = 0

    def node(self, row: int) -> core.Raft:
        return self.nodes[row + self.ID0]

    def stop(self, row: int) -> None:
        self.down.add(row + self.ID0)

    def start(self, row: int) -> None:
        self.down.discard(row + self.ID0)

    def block(self, frm: int, to: int) -> None:
        self.blocked.add((frm + self.ID0, to + self.ID0))

    def unblock(self, frm: int, to: int) -> None:
        self.blocked.discard((frm + self.ID0, to + self.ID0))

    def _drain_sends(self) -> None:
        for i, nd in self.nodes.items():
            msgs, nd.msgs = list(nd.msgs), []
            if i in self.down:
                continue
            for m in msgs:
                if m.to in self.down or (i, m.to) in self.blocked:
                    continue
                self.inflight.append((self.now + self.latency, m))

    def tick(self) -> None:
        self.now += 1
        for i, nd in self.nodes.items():
            if i not in self.down:
                nd.tick()
        self._drain_sends()
        due = [m for at, m in self.inflight if at <= self.now]
        self.inflight = [(at, m) for at, m in self.inflight
                         if at > self.now]
        for m in due:
            if m.to not in self.down:
                self.nodes[m.to].step(m)
        self._drain_sends()

    def campaign(self, row: int) -> None:
        nid = row + self.ID0
        self.nodes[nid].step(Message(type=MsgType.HUP, frm=nid))
        self._drain_sends()

    def propose(self, row: int, k: int) -> None:
        nid = row + self.ID0
        self.nodes[nid].step(Message(
            type=MsgType.PROP, frm=nid,
            entries=tuple(Entry(data=bytes([j + 1])) for j in range(k))))
        self._drain_sends()

    def leader(self):
        """Leader ROW (0-based), or None."""
        for i, nd in self.nodes.items():
            if i not in self.down and nd.state == core.LEADER:
                return i - self.ID0
        return None

    def commits(self) -> list[int]:
        return [self.nodes[i + self.ID0].log.committed
                for i in range(self.n)]


# ---------------------------------------------------------------------------
# D1'(a): commit-advance-triggered EMPTY append broadcasts are subsumed —
# caught-up edges learn the advanced commit from the next heartbeat (send-
# captured min(match, commit)) instead of an immediate empty append.
# ---------------------------------------------------------------------------

def _kernel_elect(cfg, max_ticks=300):
    st = init_state(cfg)
    for _ in range(max_ticks):
        st = _step(st, cfg)
        roles = np.asarray(st.role)
        if (roles == LEADER).any():
            return st, int(np.argmax(roles == LEADER))
    raise AssertionError("kernel never elected")


def test_d1a_commit_learned_within_one_heartbeat_of_etcd():
    cfg = SimConfig(n=5, log_len=64, window=8, apply_batch=16, max_props=8,
                    keep=4, election_tick=10, seed=260, latency=1,
                    inflight=2)
    st, L = _kernel_elect(cfg)
    kterm = int(np.asarray(st.term)[L])
    for _ in range(12):          # quiesce: noop committed everywhere
        st = _step(st, cfg)
    pay = jnp.arange(cfg.max_props, dtype=jnp.uint32) + 7
    st = _propose(st, cfg, pay, jnp.asarray(8))
    commits = []
    for _ in range(24):
        st = _step(st, cfg)
        commits.append(np.asarray(st.commit).copy())
    C = int(commits[-1][L])
    assert C == commits[0][L] + 8 or C >= 8   # the batch committed
    t_lead = next(t for t, c in enumerate(commits) if c[L] >= C)
    k_delay = max(next(t for t, c in enumerate(commits) if c[j] >= C)
                  - t_lead
                  for j in range(cfg.n) if j != L)
    # the documented bound: one heartbeat cadence + one wire hop
    assert k_delay <= cfg.heartbeat_tick + cfg.latency \
        + cfg.latency_jitter + 1, k_delay

    # unmasked etcd replay: same shape, same leader row, native
    # commit-advance bcastAppend
    net = EtcdWire(5, latency=1, election_tick=10, heartbeat_tick=1)
    for _ in range(kterm):       # reach the kernel's term
        net.campaign(L)
    for _ in range(12):
        net.tick()
    assert net.leader() == L
    assert net.node(L).term == kterm, (net.node(L).term, kterm)
    net.propose(L, 8)
    e_commits = []
    for _ in range(24):
        net.tick()
        e_commits.append(list(net.commits()))
    EC = e_commits[-1][L]
    t_lead_e = next(t for t, c in enumerate(e_commits) if c[L] >= EC)
    e_delay = max(next(t for t, c in enumerate(e_commits) if c[j] >= EC)
                  - t_lead_e
                  for j in range(5) if j != L)
    # same leader, same term, same number of entries committed past the
    # noop; kernel's propagation is at most one heartbeat cadence behind
    # etcd's immediate empty-append broadcast
    assert EC - e_commits[0][L] in (0, 8) and EC >= 8
    assert int(np.asarray(st.term)[L]) == net.nodes[L].term
    assert k_delay <= e_delay + cfg.heartbeat_tick + 1, (k_delay, e_delay)


# ---------------------------------------------------------------------------
# D1'(b): the heartbeat-response match<last append trigger is unnecessary
# because the kernel wire drops at SEND only — nothing in flight can be
# lost, so freed slots already guarantee probe retries.
# ---------------------------------------------------------------------------

def test_d1b_probe_retries_without_heartbeat_response_trigger():
    cfg = SimConfig(n=3, log_len=64, window=8, apply_batch=16, max_props=8,
                    keep=4, election_tick=10, seed=31, latency=1,
                    inflight=2)
    st, L = _kernel_elect(cfg)
    for _ in range(8):
        st = _step(st, cfg)
    j = next(i for i in range(3) if i != L)
    alive = np.ones(3, bool)
    alive[j] = False
    # follower j sleeps through 6 proposal ticks (stays within the ring)
    for t in range(6):
        pay = jnp.arange(cfg.max_props, dtype=jnp.uint32) + t * 101
        st = _propose(st, cfg, pay, jnp.asarray(4), alive=jnp.asarray(alive))
        st = _step(st, cfg, alive=jnp.asarray(alive))
    # revive j but drop the leader->j edge for 6 more ticks: every append
    # (and retry) to j dies at send; etcd would eventually lean on the
    # heartbeat-response trigger, the kernel just re-sends on free slots
    drop = np.zeros((3, 3), bool)
    drop[L, j] = True
    for _ in range(6):
        st = _step(st, cfg, drop=jnp.asarray(drop))
    heal_last = int(np.asarray(st.last)[L])
    behind = heal_last - int(np.asarray(st.last)[j])
    assert behind > 0, "scenario must leave j behind"
    # heal: j must fully catch up within the windowed-append bound
    rtt = 2 * (cfg.latency + cfg.latency_jitter) + 2
    rounds = -(-behind // cfg.window) + 2   # ceil + probe establishment
    caught_at = None
    for t in range(rounds * rtt + 10):
        st = _step(st, cfg)
        if int(np.asarray(st.commit)[j]) >= heal_last:
            caught_at = t
            break
    assert caught_at is not None, "kernel follower never caught up"

    # unmasked etcd replay (native heartbeat-resp trigger active)
    net = EtcdWire(3, latency=1, election_tick=10, heartbeat_tick=1)
    net.campaign(L)
    for _ in range(8):
        net.tick()
    assert net.leader() == L
    net.stop(j)
    for _ in range(6):
        net.propose(L, 4)
        net.tick()
    net.start(j)
    net.block(L, j)
    for _ in range(6):
        net.tick()
    e_heal_last = net.node(L).log.last_index()
    net.unblock(L, j)
    e_caught_at = None
    for t in range(rounds * rtt + 10):
        net.tick()
        if net.node(j).log.committed >= e_heal_last:
            e_caught_at = t
            break
    assert e_caught_at is not None, "etcd follower never caught up"
    # trajectory convergence: same leader, and the kernel's catch-up is
    # within a constant few ticks of etcd's despite lacking the trigger
    assert int(np.asarray(st.lead)[j]) == L \
        and net.node(j).lead == L + EtcdWire.ID0
    assert caught_at <= e_caught_at + rtt + 2, (caught_at, e_caught_at)


# ---------------------------------------------------------------------------
# D2': a PreVote rejection stamped with a receiver term ABOVE the
# candidacy's own term is dropped in the wire instead of deposing the
# pre-candidate; the lagging node converges via the next leader's appends.
# ---------------------------------------------------------------------------

def test_d2_prevote_rejection_drop_converges_to_etcd_trajectory():
    n = 3
    cfg = SimConfig(n=n, log_len=64, window=8, apply_batch=16, max_props=8,
                    keep=4, election_tick=10, seed=9090, latency=1,
                    pre_vote=True)
    st = init_state(cfg)
    # Handcraft the docstring's scenario: nodes 0,1 at term 4 with votes
    # cast (an election happened; that leader is gone), no current leader,
    # leases expired; node 2 lagging at term 3, vote free, equal log.
    # Timers pinned identically in both systems so the election ORDER is
    # deterministic (node 2 fires at tick 2 — the residue candidacy; node
    # 0 at tick 6 — the recovering election; node 1 never):
    i32 = jnp.int32
    st = dataclasses.replace(
        st,
        term=jnp.asarray([4, 4, 3], i32),
        vote=jnp.asarray([0, 0, NONE], i32),
        lead=jnp.full((n,), NONE, i32),
        contact=jnp.full((n,), cfg.election_tick + 5, i32),  # unleased
        timeout=jnp.asarray([16, 38, 10], i32),
        elapsed=jnp.asarray([10, 0, 8], i32),
    )
    k2_terms, k_lead, k_commit = [], [], []
    saw_pre_candidacy = False
    for _ in range(40):
        st = _step(st, cfg)
        roles = np.asarray(st.role)
        pre = np.asarray(st.pre)
        if roles[2] == CANDIDATE and pre[2]:
            saw_pre_candidacy = True
            # the residue live: rejections at receiver term 4 > own term 3
            # were dropped, so node 2 is NOT deposed and keeps its term
            assert int(np.asarray(st.term)[2]) == 3
        k2_terms.append(int(np.asarray(st.term)[2]))
        k_lead.append(np.asarray(st.lead).copy())
        k_commit.append(np.asarray(st.commit).copy())
    assert saw_pre_candidacy, "node 2 never entered the residue scenario"

    # unmasked etcd-faithful replay: same handcrafted state; native
    # behavior deposes node 2 to term 4 on the first higher-term rejection
    net = EtcdWire(n, latency=1, election_tick=10, pre_vote=True,
                   check_quorum=True, seed=77)
    # rebuild the three nodes with the handcrafted hard state (1-based
    # ids; "voted for row 0" = vote=1)
    for row, hs, seed in ((0, HardState(term=4, vote=1, commit=0), 77),
                          (1, HardState(term=4, vote=1, commit=0), 108),
                          (2, HardState(term=3, vote=0, commit=0), 139)):
        net.nodes[row + 1] = core.Raft(core.Config(
            id=row + 1, peers=(1, 2, 3), election_tick=10,
            heartbeat_tick=1, pre_vote=True, check_quorum=True,
            seed=seed), hard_state=hs)
    for i, nd in net.nodes.items():
        nd.contact_elapsed = cfg.election_tick + 5        # unleased
    # same pinned firing order: node 2 at tick 2, node 0 at 6, node 1 never
    net.node(0).randomized_election_timeout = 16
    net.node(0).election_elapsed = 10
    net.node(1).randomized_election_timeout = 38
    net.node(1).election_elapsed = 0
    net.node(2).randomized_election_timeout = 10
    net.node(2).election_elapsed = 8
    deposed_at = None
    for t in range(40):
        net.tick()
        if deposed_at is None and net.node(2).term == 4 \
                and net.node(2).state == core.FOLLOWER \
                and net.leader() is None:
            deposed_at = t   # etcd's immediate higher-term deposal
    # the DIVERGENCE is real: etcd deposed node 2 to term 4 before any
    # election; the kernel kept it pre-campaigning at term 3
    assert deposed_at is not None
    assert any(kt == 3 for kt in k2_terms[deposed_at:deposed_at + 2])

    # ... and the TRAJECTORIES CONVERGE: node 0's later campaign wins in
    # both systems; same leader, same term, same commit, everywhere
    k_roles = np.asarray(st.role)
    assert int(np.argmax(k_roles == LEADER)) == 0 and net.leader() == 0
    k_final_term = np.asarray(st.term)
    e_final_term = [net.node(i).term for i in range(n)]
    assert k_final_term.tolist() == e_final_term
    k_final_commit = np.asarray(st.commit)
    e_final_commit = net.commits()
    assert k_final_commit.tolist() == e_final_commit
    assert int(k_final_commit[2]) >= 1   # node 2 caught up via appends
    assert np.asarray(st.role)[2] == FOLLOWER \
        and net.node(2).state == core.FOLLOWER

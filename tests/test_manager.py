"""Manager wiring tests: leadership-driven subsystem lifecycle, default
seeding, control-api + dispatcher + agent against a real raft quorum.

Reference scenarios: manager/manager_test.go + the leader flip matrix in
integration/integration_test.go.
"""

import asyncio
import os
import tempfile

import pytest

from swarmkit_tpu.agent import Agent, AgentConfig
from swarmkit_tpu.agent.testutils import TestExecutor
from swarmkit_tpu.api import (
    Annotations, ContainerSpec, MembershipState, NodeRole, NodeSpec,
    ReplicatedService, ServiceSpec, TaskSpec, TaskState,
)
from swarmkit_tpu.api.objects import Node as ApiNode, NodeStatus
from swarmkit_tpu.manager.health import HealthStatus
from swarmkit_tpu.manager.manager import Manager
from swarmkit_tpu.raft.transport import Network
from swarmkit_tpu.store.by import ByService
from swarmkit_tpu.utils.clock import FakeClock
from tests.conftest import async_test, requires_cryptography

TICK = 1.0


class ManagerHarness:
    def __init__(self):
        self.clock = FakeClock()
        self.network = Network(seed=11)
        self.tmp = tempfile.TemporaryDirectory(prefix="swarmkit-mgr-")
        self.managers: list[Manager] = []

    def new_manager(self, i: int, join_addr: str = "") -> Manager:
        m = Manager(node_id=f"m{i}", addr=f"m{i}.test:4242",
                    network=self.network,
                    state_dir=os.path.join(self.tmp.name, f"m{i}"),
                    clock=self.clock, join_addr=join_addr,
                    election_tick=4, heartbeat_tick=1, seed=31 + i)
        self.managers.append(m)
        return m

    async def pump(self, seconds=1.0, steps=8):
        for _ in range(steps):
            await asyncio.sleep(0)
        await self.clock.advance(seconds)
        for _ in range(steps):
            await asyncio.sleep(0)

    async def settle(self, ticks=12):
        for _ in range(ticks):
            await self.pump(TICK)

    def leader(self):
        for m in self.managers:
            if m.is_leader():
                return m
        return None

    async def wait_leader(self, ticks=40):
        for _ in range(ticks):
            await self.pump(TICK)
            lead = self.leader()
            if lead is not None and lead._is_leader:
                return lead
        raise AssertionError("no leader elected")

    async def stop_all(self):
        for m in self.managers:
            try:
                await m.stop()
            except Exception:
                pass


def service_spec(name="web", replicas=2):
    return ServiceSpec(annotations=Annotations(name=name),
                       task=TaskSpec(container=ContainerSpec(image="img")),
                       replicated=ReplicatedService(replicas=replicas))


@async_test
@requires_cryptography
async def test_single_manager_bootstrap_seeds_defaults():
    h = ManagerHarness()
    m = h.new_manager(1)
    await m.start()
    lead = await h.wait_leader()
    assert lead is m
    # default cluster + own node object exist (manager.go:931-983)
    clusters = m.store.find("cluster")
    assert len(clusters) == 1
    assert clusters[0].root_ca.join_token_worker.startswith("SWMTKN-1-")
    me = m.store.get("node", "m1")
    assert me is not None and me.role == NodeRole.MANAGER
    assert m.health.check("Raft") == HealthStatus.SERVING
    assert m.metrics.snapshot()["swarm_manager_leader"] == 1.0
    await h.stop_all()


@async_test
async def test_service_create_schedules_and_runs_on_agent_nodes():
    h = ManagerHarness()
    m = h.new_manager(1)
    await m.start()
    await h.wait_leader()

    # register two worker node records (the CA-join analog), then agents
    for i in (1, 2):
        await m.store.update(lambda tx, i=i: tx.create(ApiNode(
            id=f"w{i}", spec=NodeSpec(annotations=Annotations(name=f"w{i}"),
                                      membership=MembershipState.ACCEPTED),
            status=NodeStatus())))
    agents = []
    for i in (1, 2):
        a = Agent(AgentConfig(node_id=f"w{i}",
                              executor=TestExecutor(hostname=f"w{i}"),
                              connect=lambda: m.dispatcher,
                              clock=h.clock))
        await a.start()
        agents.append(a)
    await h.settle(4)

    svc = await m.control_api.create_service(service_spec(replicas=3))
    for _ in range(120):
        await h.pump(0.25)
        running = [t for t in m.store.find("task", ByService(svc.id))
                   if t.status.state == TaskState.RUNNING]
        if len(running) == 3:
            break
    else:
        tasks = m.store.find("task", ByService(svc.id))
        raise AssertionError(
            f"not running: {[(t.id, int(t.status.state), t.node_id) for t in tasks]}")
    nodes_used = {t.node_id for t in m.store.find("task", ByService(svc.id))}
    assert nodes_used <= {"w1", "w2"} and len(nodes_used) == 2
    for a in agents:
        await a.stop()
    await h.stop_all()


@async_test
async def test_leadership_failover_moves_control_loops():
    h = ManagerHarness()
    m1 = h.new_manager(1)
    await m1.start()
    await h.wait_leader()
    m2 = h.new_manager(2, join_addr=m1.addr)
    await m2.start()
    m3 = h.new_manager(3, join_addr=m1.addr)
    await m3.start()
    await h.settle(8)
    assert m1._is_leader and not m2._is_leader and not m3._is_leader
    # all three have the seeded cluster replicated
    for m in (m2, m3):
        assert len(m.store.find("cluster")) == 1

    # kill the leader -> one of the others takes over and starts loops
    await m1.stop()
    for _ in range(60):
        await h.pump(TICK)
        lead = next((m for m in (m2, m3) if m._is_leader), None)
        if lead is not None:
            break
    else:
        raise AssertionError("no new leader became active")
    assert lead._leader_components, "leader components not started"

    # the new leader can take writes end-to-end
    svc = await lead.control_api.create_service(service_spec(name="after"))
    assert lead.store.get("service", svc.id) is not None
    await h.stop_all()


@async_test
async def test_manager_is_state_dirty():
    h = ManagerHarness()
    m = h.new_manager(1)
    await m.start()
    await h.wait_leader()
    assert not m.is_state_dirty()
    await m.control_api.create_service(service_spec())
    assert m.is_state_dirty()
    await h.stop_all()

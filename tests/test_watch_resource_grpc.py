"""Watch + ResourceAllocator served cross-process over gRPC.

A swarmd manager and a joined swarmd worker on real loopback sockets:
the worker reaches the manager's resourceapi (network attach/detach)
through its RemoteManager, and an operator-side RemoteManager streams
store events through the watchapi Watch RPC.

Reference: manager/watchapi/server.go and manager/resourceapi/allocator.go
— both registered on the manager's gRPC server in manager.go:526-548; the
clients here are the duck types in swarmkit_tpu/rpc.py.
"""

import asyncio
import os
import socket
import tempfile

import pytest

from swarmkit_tpu.api import (
    Annotations, MembershipState, NetworkSpec, NodeSpec, NodeState,
)
from swarmkit_tpu.api.objects import Node as ApiNode, NodeStatus
from swarmkit_tpu.ca.certificates import HAVE_CRYPTOGRAPHY
from tests.conftest import async_test


def _free_port() -> int:
    with socket.socket() as s:
        s.bind(("127.0.0.1", 0))
        return s.getsockname()[1]


async def _poll(fn, what: str, timeout: float = 20.0):
    deadline = asyncio.get_running_loop().time() + timeout
    while True:
        val = fn()
        if val:
            return val
        if asyncio.get_running_loop().time() > deadline:
            raise AssertionError(f"timeout waiting for {what}")
        await asyncio.sleep(0.05)


@async_test
@pytest.mark.skipif(
    HAVE_CRYPTOGRAPHY,
    reason="exercises the identityless wire; the mTLS join path is covered "
           "by tests/test_grpc_transport.py")
async def test_worker_reaches_watch_and_resourceapi_over_grpc():
    from swarmkit_tpu.cmd import swarmd
    from swarmkit_tpu.manager.resourceapi import ResourceError
    from swarmkit_tpu.manager.watchapi import WatchSelector
    from swarmkit_tpu.rpc import RemoteManager

    tmp = tempfile.TemporaryDirectory(prefix="grpc-watchres-")
    m_addr = f"127.0.0.1:{_free_port()}"
    m_args = swarmd.build_parser().parse_args([
        "--state-dir", os.path.join(tmp.name, "m1"),
        "--listen-control-api", os.path.join(tmp.name, "m1.sock"),
        "--listen-remote-api", m_addr,
        "--node-id", "m1", "--manager", "--election-tick", "4",
        "--executor", "test",
    ])
    manager_node = await swarmd.run(m_args)
    worker_node = None
    operator = None
    try:
        await _poll(manager_node.is_leader, "manager leadership")
        lead = manager_node._running_manager()
        await _poll(lambda: lead.store.find("cluster"), "cluster object")

        # identityless worker join: the operator pre-creates the node
        # record (node/node.py: "legacy identityless worker")
        await lead.store.update(lambda tx: tx.create(ApiNode(
            id="w1",
            spec=NodeSpec(annotations=Annotations(name="w1"),
                          membership=MembershipState.ACCEPTED),
            status=NodeStatus())))
        w_addr = f"127.0.0.1:{_free_port()}"
        w_args = swarmd.build_parser().parse_args([
            "--state-dir", os.path.join(tmp.name, "w1"),
            "--listen-control-api", os.path.join(tmp.name, "w1.sock"),
            "--listen-remote-api", w_addr,
            "--node-id", "w1", "--join-addr", m_addr,
            "--executor", "test",
        ])
        worker_node = await swarmd.run(w_args)

        # the dispatcher session marks the worker READY in the manager's
        # store — proof the join went over the sockets
        await _poll(
            lambda: (n := lead.store.get("node", "w1")) is not None
            and n.status.state == NodeState.READY, "worker READY")

        # -- resourceapi through the worker's own RemoteManager ----------
        net_obj = await lead.control_api.create_network(
            NetworkSpec(annotations=Annotations(name="overlay1")))
        rm = await _poll(
            lambda: next((r for r in worker_node._remote_managers.values()
                          if r.resource_api is not None), None),
            "worker's RemoteManager connected")

        attachment_id = await rm.resource_api.attach_network(
            "w1", net_obj.id)
        task = lead.store.get("task", attachment_id)
        assert task is not None and task.node_id == "w1"
        assert net_obj.id in task.spec.networks

        # unknown network id is a typed ResourceError across the wire
        try:
            await rm.resource_api.attach_network("w1", "no-such-network")
        except ResourceError:
            pass
        else:
            raise AssertionError("attach of unknown network must raise "
                                 "ResourceError")

        await rm.resource_api.detach_network(attachment_id)
        await _poll(lambda: lead.store.get("task", attachment_id) is None,
                    "attachment removed")

        # -- watchapi from an operator-side RemoteManager ----------------
        operator = RemoteManager(m_addr)
        operator.start()
        await operator.refresh()
        assert operator.watch_server is not None

        stream = operator.watch_server.watch(
            selectors=[WatchSelector(kind="network", actions=("create",))])
        first = asyncio.ensure_future(stream.__anext__())
        await asyncio.sleep(0.3)   # let the server-side subscription arm
        created = await lead.control_api.create_network(
            NetworkSpec(annotations=Annotations(name="overlay2")))
        msg = await asyncio.wait_for(first, timeout=10)
        assert msg.action == "create" and msg.kind == "network"
        assert msg.object.id == created.id
        first = asyncio.ensure_future(stream.__anext__())
        first.cancel()
    finally:
        if operator is not None:
            await operator.close()
        if worker_node is not None:
            await worker_node._ctl_server.stop()
            await worker_node.stop()
            for r in getattr(worker_node, "_remote_managers", {}).values():
                await r.close()
        await manager_node._ctl_server.stop()
        await manager_node.stop()
        for r in getattr(manager_node, "_remote_managers", {}).values():
            await r.close()
        net = manager_node.config.network
        if hasattr(net, "close"):
            await net.close()
        tmp.cleanup()

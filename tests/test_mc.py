"""The exhaustive model checker (swarmkit_tpu/mc/).

Tier-1 here is the smoke scope (n=3, horizon 4): one shared exhaustive
scan fixture feeds the level-count, dedup, budget, LTS-export and CLI
assertions, so the expand program compiles once per process.  The
headline n3h8 scope — the full 13^8 schedule space, the >= 1M
branches-per-pass claim, and the two mutation catch-and-replay
self-tests — runs under ``@pytest.mark.slow`` (minutes of wall).
"""

from __future__ import annotations

import json
import os
import subprocess
import sys

import dataclasses

import jax.numpy as jnp
import numpy as np
import pytest

from swarmkit_tpu import mc
from swarmkit_tpu.dst import repro
from swarmkit_tpu.dst.schedule import apply_term_inflation, make_schedule
from swarmkit_tpu.mc.fingerprint import fingerprint, relabel_state
from swarmkit_tpu.raft.sim.state import LEADER, init_state

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

from tools import mc_export, mc_sweep  # noqa: E402

SMOKE = mc.SCOPES["smoke"]

# the smoke scope's exact per-level (children, unique) ladder; a change
# here means the kernel's reachable behavior changed and every documented
# scope claim needs re-measuring
SMOKE_LEVELS = ((13, 4), (52, 29), (377, 225), (2925, 1403))


# ---------------------------------------------------------------------------
# branch space


def test_alphabet_sizes_and_names():
    for n, want in ((3, 13), (4, 24), (5, 41)):
        alpha = mc.build_alphabet(n)
        assert alpha.size == want
        assert len(set(alpha.names)) == alpha.size  # labels unique
        assert alpha.names[0] == "noop"
        assert alpha.alive.shape == (want, n)
        assert alpha.drop.shape == (want, n, n)
        assert alpha.inflate is None
    alpha = mc.build_alphabet(3, term_inflation=True)
    assert alpha.size == 16 and alpha.inflate is not None


def test_alphabet_action_semantics():
    alpha = mc.build_alphabet(3)
    by_name = {nm: k for k, nm in enumerate(alpha.names)}
    assert not alpha.alive[by_name["crash_1"], 1]
    assert alpha.alive[by_name["crash_1"], 0]
    assert alpha.drop[by_name["drop_0to2"], 0, 2]
    assert not alpha.drop[by_name["drop_0to2"], 2, 0]
    part = alpha.drop[by_name["part_0v12"]]
    assert part[0, 1] and part[1, 0] and part[0, 2] and part[2, 0]
    assert not part[1, 2] and not part[2, 1]


def test_branch_path_roundtrip():
    for branch in (0, 1, 12, 13, 28560, 123456):
        path = mc.branch_to_path(branch, 13, 8)
        assert len(path) == 8
        assert mc.path_to_branch(path, 13) == branch
    with pytest.raises(ValueError):
        mc.branch_to_path(13 ** 4, 13, 4)
    with pytest.raises(ValueError):
        mc.path_to_branch([13], 13)


def test_path_to_schedule_lowering():
    alpha = mc.build_alphabet(3, term_inflation=True)
    by_name = {nm: k for k, nm in enumerate(alpha.names)}
    path = [by_name["crash_2"], by_name["noop"], by_name["inflate_0"]]
    sched = mc.path_to_schedule(alpha, path)
    assert sched.ticks == 3
    alive = np.asarray(sched.alive)
    assert not alive[0, 2] and alive[0, 0] and alive[1].all()
    ti = np.asarray(sched.term_inflate)
    assert ti[2, 0] and not ti[2, 1] and not ti[0].any()
    # scopes without term_inflation lower to the pre-extension pytree
    assert mc.path_to_schedule(mc.build_alphabet(3), [0]).term_inflate is None


def test_scope_presets():
    assert SMOKE.space_size() == 13 ** 4
    n3h8 = mc.SCOPES["n3h8"]
    assert n3h8.n == 3 and n3h8.horizon == 8 and n3h8.budget is None
    cfg = n3h8.cfg()
    assert cfg.read_batch >= 1  # LINEARIZABLE_READ armed
    assert mc.SCOPES["n3h12"].budget  # deep scope ships budget-bounded


# ---------------------------------------------------------------------------
# fingerprints


def test_fingerprint_deterministic_and_sensitive():
    cfg = SMOKE.cfg()
    st = init_state(cfg)
    f1 = np.asarray(fingerprint(st))
    f2 = np.asarray(fingerprint(st))
    assert (f1 == f2).all()
    bumped = dataclasses.replace(st, term=st.term.at[1].add(1))
    assert (np.asarray(fingerprint(bumped)) != f1).any()
    # position sensitivity: swapping two equal-valued rows' terms is
    # invisible to a value-only hash; the positional salt must see it
    st2 = dataclasses.replace(st, term=st.term.at[0].set(5))
    st3 = dataclasses.replace(st, term=st.term.at[2].set(5))
    assert (np.asarray(fingerprint(st2))
            != np.asarray(fingerprint(st3))).any()


def test_relabel_collapses_symmetric_states():
    cfg = SMOKE.cfg()
    st = init_state(cfg)
    # a state with per-row structure, and its relabeling under a
    # nontrivial permutation: plain fingerprints differ (relabeling is
    # visible), canonical fingerprints collapse to one value.  NOTE the
    # partner must be built by relabel_state — two hand-built "mirror"
    # states are NOT symmetric, because init_state's randomized timeouts
    # key on the row index (the documented reason symmetry dedup is a
    # heuristic).
    a = dataclasses.replace(st, term=st.term.at[0].set(3),
                            vote=st.vote.at[0].set(0))
    b = relabel_state(a, [2, 0, 1])
    assert (np.asarray(fingerprint(a)) != np.asarray(fingerprint(b))).any()
    ca = np.asarray(mc.canonical_fingerprint(a, cfg.n))
    cb = np.asarray(mc.canonical_fingerprint(b, cfg.n))
    assert (ca == cb).all()
    # relabeling composes like a permutation action: perm then inverse
    # is the identity
    rr = relabel_state(b, [1, 2, 0])
    assert (np.asarray(fingerprint(rr)) == np.asarray(fingerprint(a))).all()


def test_relabel_distinct_states_stay_distinct():
    cfg = SMOKE.cfg()
    st = init_state(cfg)
    a = dataclasses.replace(st, term=st.term.at[0].set(3))
    b = dataclasses.replace(st, term=st.term.at[0].set(4))  # no relabeling maps 3 to 4
    ca = np.asarray(mc.canonical_fingerprint(a, cfg.n))
    cb = np.asarray(mc.canonical_fingerprint(b, cfg.n))
    assert (ca != cb).any()


def test_fingerprint_stable_across_processes():
    """The fold keys off splitmix32, not python hashing: a subprocess
    with a different PYTHONHASHSEED must compute the identical value."""
    cfg = SMOKE.cfg()
    here = [int(x) for x in np.asarray(fingerprint(init_state(cfg)))]
    prog = (
        "import numpy as np\n"
        "from swarmkit_tpu.mc import SCOPES\n"
        "from swarmkit_tpu.mc.fingerprint import fingerprint\n"
        "from swarmkit_tpu.raft.sim.state import init_state\n"
        "fp = np.asarray(fingerprint(init_state(SCOPES['smoke'].cfg())))\n"
        "print(int(fp[0]), int(fp[1]))\n")
    env = dict(os.environ, PYTHONHASHSEED="12345", JAX_PLATFORMS="cpu")
    out = subprocess.run([sys.executable, "-c", prog], env=env,
                         capture_output=True, text=True, timeout=240,
                         cwd=os.path.dirname(os.path.dirname(
                             os.path.abspath(__file__))))
    assert out.returncode == 0, out.stderr[-2000:]
    assert [int(x) for x in out.stdout.split()] == here


# ---------------------------------------------------------------------------
# the smoke-scope exhaustive scan (shared: one compile per process)


@pytest.fixture(scope="module")
def smoke_scan():
    return mc.exhaustive_scan(SMOKE.cfg(), SMOKE.alphabet(), SMOKE.horizon,
                              prop_count=SMOKE.prop_count,
                              collect_edges=True, scope="smoke")


def test_smoke_scan_exhaustive_and_clean(smoke_scan):
    res = smoke_scan
    assert tuple((lv["children"], lv["unique"]) for lv in res.levels) \
        == SMOKE_LEVELS
    assert not res.violations
    assert res.exhaustive and not res.truncated
    assert res.branches_explored == sum(c for c, _ in SMOKE_LEVELS)
    assert res.states_discovered == 1 + sum(u for _, u in SMOKE_LEVELS)
    assert res.schedule_space == 13 ** 4
    summary = res.summary()
    json.dumps(summary)  # JSON-able end to end
    assert summary["exhaustive"] is True


def test_smoke_scan_dedup_merges_duplicates(smoke_scan):
    # the whole point of the frontier: 2925 level-4 children collapse to
    # 1403 unique states, so deeper levels stay tractable
    lv = smoke_scan.levels[-1]
    assert lv["duplicates"] == lv["children"] - lv["unique"]
    assert smoke_scan.duplicates == sum(l["duplicates"]
                                        for l in smoke_scan.levels)


def test_budget_truncation_is_loud():
    res = mc.exhaustive_scan(SMOKE.cfg(), SMOKE.alphabet(), SMOKE.horizon,
                             prop_count=SMOKE.prop_count, budget=16,
                             scope="smoke")
    assert res.truncated and not res.exhaustive
    assert any(lv["truncated"] > 0 for lv in res.levels)
    assert all(lv["unique"] <= 16 for lv in res.levels)
    assert res.summary()["exhaustive"] is False


def test_aut_export_roundtrip(smoke_scan, tmp_path):
    path = str(tmp_path / "smoke.aut")
    mc_export.write_aut(path, smoke_scan.edges, smoke_scan.num_states,
                        SMOKE.alphabet().names)
    assert mc_export.validate_aut(path) == []
    with open(path, encoding="utf-8") as f:
        header = f.readline().strip()
    assert header == (f"des (0, {len(smoke_scan.edges)}, "
                      f"{smoke_scan.num_states})")
    # the validator actually rejects broken files
    with open(path, encoding="utf-8") as f:
        lines = f.read().splitlines()
    bad = str(tmp_path / "bad.aut")
    with open(bad, "w", encoding="utf-8") as f:
        f.write("\n".join([lines[0]] + lines[2:]))  # drop one transition
    assert mc_export.validate_aut(bad)
    with open(bad, "w", encoding="utf-8") as f:
        f.write("\n".join(lines[1:]))  # no header
    assert mc_export.validate_aut(bad)


def test_mc_sweep_cli_smoke(tmp_path, capsys):
    out = str(tmp_path / "summary.json")
    rc = mc_sweep.main(["--smoke", "--json", out])
    assert rc == 0
    assert "PASS" in capsys.readouterr().out
    with open(out, encoding="utf-8") as f:
        summary = json.load(f)
    assert summary["exhaustive"] is True and summary["violations"] == []
    assert summary["branches_explored"] == sum(c for c, _ in SMOKE_LEVELS)


# ---------------------------------------------------------------------------
# term_inflation (the new FaultSchedule verb)


def test_apply_term_inflation_forces_timer():
    cfg = SMOKE.cfg()
    st = init_state(cfg)
    force = jnp.asarray(np.array([False, True, False]))
    alive = jnp.ones((3,), bool)
    out = apply_term_inflation(st, force, alive)
    assert int(out.elapsed[1]) == int(st.timeout[1])
    assert int(out.elapsed[0]) == int(st.elapsed[0])
    # leaders are exempt: inflation models a NON-leader spinning its timer
    led = dataclasses.replace(st, role=st.role.at[1].set(LEADER))
    out = apply_term_inflation(led, force, alive)
    assert int(out.elapsed[1]) == int(led.elapsed[1])


def test_term_inflation_schedule_generator():
    cfg = SMOKE.cfg()
    sched = make_schedule(cfg, 24, "term_inflation", seed=3)
    ti = np.asarray(sched.term_inflate)
    assert ti.shape == (24, cfg.n) and ti.any()
    victims = set(np.nonzero(ti)[1].tolist())
    assert len(victims) == 1  # one victim row per schedule
    # the victim is partitioned away on exactly its inflation windows
    # (otherwise same-tick heartbeats reset the forced timer)
    drop = np.asarray(sched.drop)
    v = victims.pop()
    gate = ti[:, v]
    assert (drop[gate][:, v, :].sum(axis=-1) >= cfg.n - 1).all()
    assert not drop[~gate].any()


def test_term_inflation_artifact_roundtrip(tmp_path):
    cfg = SMOKE.cfg()
    sched = make_schedule(cfg, 12, "term_inflation", seed=3)
    viol, first = repro.replay(cfg, sched, 1, None)
    art = repro.to_artifact(cfg, sched, seed=3, profile="term_inflation",
                            index=0, prop_count=1, mutation=None,
                            viol=viol, first_tick=first)
    assert "term_inflate" in art["faults"]
    path = str(tmp_path / "ti.json")
    repro.save_artifact(path, art)
    verdict = repro.replay_artifact(path, with_trace=False)
    assert verdict["matches_recorded"]
    # pre-extension artifacts (no term_inflate key) still load as None
    del art["faults"]["term_inflate"]
    _, sched2, _, _ = repro.from_artifact(art)
    assert sched2.term_inflate is None


# ---------------------------------------------------------------------------
# slow: the documented n3h8 claims


@pytest.mark.slow
def test_prevote_neutralizes_term_inflation():
    from tools.dst_sweep import run_term_inflation_demo
    demo = run_term_inflation_demo(schedules=8, ticks=60, seed=7,
                                   verbose=False)
    assert demo["neutralized"]
    assert demo["no_prevote"]["violations"] == 0
    assert demo["prevote"]["violations"] == 0
    assert demo["no_prevote"]["max_term"] >= 10
    assert demo["prevote"]["max_term"] <= 3


@pytest.mark.slow
@pytest.mark.parametrize("mutation", ["commit_no_quorum",
                                      "stale_lease_read"])
def test_mutation_caught_by_exhaustive_scan(mutation, tmp_path):
    """The enumeration MUST catch both seeded bugs at n=3 / horizon 8,
    and the counterexample must survive the lower -> shrink -> artifact
    -> replay round trip exactly."""
    demo = mc_sweep.run_self_test(
        "n3h8", mutation, out_path=str(tmp_path / "repro.json"),
        verbose=False)
    assert demo["caught"], f"{mutation} escaped the exhaustive scan"
    assert demo["replay_matches"]
    art = repro.load_artifact(demo["artifact"])
    assert art["profile"] == "mc:n3h8"
    assert art["mc"]["actions"]
    assert art["violation_bits"] != 0


@pytest.mark.slow
def test_n3h8_full_scope_is_clean_and_wide():
    """The headline claim: the full 13^8 schedule space at n=3 collapses
    to ~3.5M explored branches / ~1.3M reachable states with ZERO
    invariant violations, and the big levels run >= 1M real branches in
    a single device pass."""
    res = mc_sweep.run_scan("n3h8", verbose=False)
    assert not res.violations
    assert res.exhaustive
    assert res.branches_explored >= 3_000_000
    assert res.max_branches_per_pass >= 1_000_000
    assert res.levels[0]["unique"] == 4  # ladder anchor
    assert res.schedule_space == 13 ** 8

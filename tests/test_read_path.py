"""Linearizable read path tests (swarmkit_tpu/raft/read/).

The load-bearing guarantees:

- ``read_batch=0`` (the default) must leave the kernel program untouched —
  every non-read SimState field bit-identical to a run that never knew the
  read path existed, on all three wires (the read phases are gated in
  Python, so they are simply not traced).
- Lease safety: the tick-clock lease expires strictly before any rival can
  assemble an election quorum, so a partitioned stale leader refuses reads
  instead of serving state missing the successor's committed writes —
  including across a leader crash mid-lease.
- The LINEARIZABLE_READ DST invariant catches a lease-disabled stale serve
  (the ``stale_lease_read`` mutation) under the pinned-victim
  ``stale_leader_reads`` adversary.
"""

import dataclasses

import jax.numpy as jnp
import numpy as np
import pytest

from swarmkit_tpu.dst.invariants import LINEARIZABLE_READ, check_state
from swarmkit_tpu.raft import read as rd
from swarmkit_tpu.raft.read import lease
from swarmkit_tpu.raft.sim import (
    LEADER, NONE, SimConfig, SimState, init_state, leader_mask,
    reads_blocked, reads_served, run_schedule, run_ticks, run_until_leader,
    submit_reads,
)

I32 = jnp.int32


def small_cfg(**kw):
    base = dict(n=5, log_len=64, window=8, apply_batch=16, max_props=8,
                keep=4, election_tick=10, seed=3)
    base.update(kw)
    return SimConfig(**base)


WIRES = {
    "sync": {},
    "force_mailboxes": {"force_mailboxes": True},
    "mailbox_lat2": {"latency": 2, "latency_jitter": 1, "inflight": 4},
}


# ---------------------------------------------------------------------------
# config validation + lease arithmetic


def test_read_batch_rejects_negative():
    with pytest.raises(ValueError, match="read_batch"):
        small_cfg(read_batch=-1)


def test_lease_margin_must_cover_clock_skew():
    with pytest.raises(ValueError, match="lease_margin"):
        small_cfg(read_batch=2, lease_margin=0)


def test_lease_ticks_must_be_positive():
    # election_tick 10 - margin 7 - (latency 2 + jitter 1) = 0: the margin
    # plus wire staleness consume the whole timeout, no lease span left
    with pytest.raises(ValueError, match="lease_ticks"):
        small_cfg(read_batch=2, latency=2, latency_jitter=1, lease_margin=7)
    # ReadIndex-only serving with the same knobs is fine
    small_cfg(read_batch=2, latency=2, latency_jitter=1, lease_margin=7,
              read_leases=False)


def test_lease_ticks_arithmetic():
    assert small_cfg(read_batch=2).lease_ticks == 9
    assert small_cfg(read_batch=2, latency=2,
                     latency_jitter=1).lease_ticks == 6
    cfg = small_cfg(read_batch=2, lease_margin=3)
    assert cfg.lease_ticks == 7
    assert lease.lease_span(cfg) == cfg.lease_ticks


def test_lease_renew_and_valid_semantics():
    cfg = small_cfg(read_batch=2)
    n = cfg.n
    role = jnp.asarray([LEADER, 0, 0, LEADER, 0], I32)
    q_ok = jnp.asarray([True, False, False, False, False])
    transferee = jnp.full((n,), NONE, I32).at[3].set(1)
    now = jnp.asarray(20, I32)
    prev = jnp.full((n,), 15, I32)
    until = lease.renew(cfg, prev, role, q_ok, transferee, now)
    # quorum ack grants now + span; non-leaders are cleared to 0 so a new
    # leader starts lease-less; an in-flight transfer blocks the grant
    assert int(until[0]) == 20 + cfg.lease_ticks
    assert int(until[1]) == 0 and int(until[2]) == 0
    assert int(until[3]) == 15    # leader, but transferring: no renewal

    is_leader = role == LEADER
    ok = lease.valid(cfg, until, is_leader, transferee, now)
    assert bool(ok[0])
    assert not bool(ok[3])        # transfer voids the lease
    assert not bool(ok[1])
    # expiry is strict: now == lease_until is already invalid
    at_edge = jnp.full((n,), 20, I32)
    assert not bool(lease.valid(cfg, at_edge, is_leader, transferee, now)[0])
    # leases disabled: never valid, regardless of state
    cfg_off = small_cfg(read_batch=2, read_leases=False)
    assert not bool(lease.valid(cfg_off, until, is_leader, transferee,
                                now)[0])


# ---------------------------------------------------------------------------
# read_batch=0 bit-identity (the acceptance regression)


@pytest.mark.parametrize("wire", sorted(WIRES))
def test_reads_off_is_bit_identical(wire):
    """With read_batch=0 every kernel output matches a run of the identical
    config with reads on — the read path only ADDS the read_*/lease_*
    registers, it never perturbs the sim."""
    cfg_off = small_cfg(**WIRES[wire])
    cfg_on = small_cfg(read_batch=2, **WIRES[wire])
    off, _ = run_ticks(init_state(cfg_off), cfg_off, 50, prop_count=1)
    on, _ = run_ticks(init_state(cfg_on), cfg_on, 50, prop_count=1)
    assert off.read_pend is None and on.read_pend is not None
    for f in dataclasses.fields(SimState):
        if f.name.startswith(("read_", "lease_")):
            continue
        a, b = getattr(off, f.name), getattr(on, f.name)
        if a is None:
            assert b is None, f.name
            continue
        assert np.array_equal(np.asarray(a), np.asarray(b)), \
            f"field {f.name} diverged with reads on ({wire} wire)"


def test_reads_off_registers_are_none():
    st = init_state(small_cfg())
    assert st.read_pend is None and st.read_srv is None
    assert st.lease_until is None
    assert int(reads_served(st)) == 0 and int(reads_blocked(st)) == 0


# ---------------------------------------------------------------------------
# serving behavior


def _settled(cfg, warm_ticks=30):
    st = init_state(cfg)
    st, _ = run_until_leader(st, cfg, max_ticks=200)
    st, _ = run_ticks(st, cfg, warm_ticks, prop_count=2)
    return st


@pytest.mark.parametrize("leases", [True, False])
def test_steady_state_serves_reads(leases):
    cfg = small_cfg(read_batch=4, read_leases=leases)
    st = _settled(cfg)
    before = int(reads_served(st))
    fin, _ = run_ticks(st, cfg, 20, prop_count=2)
    served = int(reads_served(fin)) - before
    # the leader serves every tick; followers settle one stamp round later
    assert served >= 20 * cfg.read_batch
    assert int(check_state(fin, cfg)) == 0
    assert bool(jnp.all(fin.read_srv_idx >= fin.read_srv_goal))


def test_submit_reads_host_api():
    cfg = small_cfg(read_batch=2)
    st = init_state(cfg)
    st = submit_reads(st, cfg, 7, rows=[0, 2])
    assert st.read_pend.tolist() == [7, 0, 7, 0, 0]
    assert int(st.read_idx[0]) == NONE
    # occupied rows keep their batch: a second submit is a no-op there
    again = submit_reads(st, cfg, 3, rows=[0, 1])
    assert again.read_pend.tolist() == [7, 3, 7, 0, 0]
    # the batches drain through the normal step flow
    fin, _ = run_ticks(again, cfg, 40, prop_count=1)
    assert int(reads_served(fin)) + int(reads_blocked(fin)) >= 17
    with pytest.raises(ValueError, match="read path is off"):
        submit_reads(init_state(small_cfg()), small_cfg(), 1)


def test_stale_leader_partition_refuses_reads():
    """Isolate the sitting leader: its lease expires inside the window and
    it must stop serving (bounded by the lease span) and refuse the rest,
    while the majority elects a successor and read linearizability holds."""
    cfg = small_cfg(read_batch=2)
    st = _settled(cfg)
    lm = np.asarray(leader_mask(st))
    assert lm.any()
    ldr = int(np.argmax(lm))
    srv_before = int(st.read_srv[ldr])
    ticks = 60
    drop = np.zeros((ticks, cfg.n, cfg.n), bool)
    drop[:, ldr, :] = True
    drop[:, :, ldr] = True
    fin, _ = run_schedule(st, cfg, jnp.asarray(drop),
                          jnp.ones((ticks, cfg.n), bool), prop_count=2)
    assert int(check_state(fin, cfg)) == 0
    assert bool(jnp.all(fin.read_srv_idx >= fin.read_srv_goal))
    # served only while the lease was still valid, then refused
    served = int(fin.read_srv[ldr]) - srv_before
    assert served <= (cfg.lease_ticks + 1) * cfg.read_batch
    assert int(fin.read_block[ldr]) > 0
    # the majority moved on: a successor leads and commits
    lm_fin = np.asarray(leader_mask(fin))
    others = np.arange(cfg.n) != ldr
    assert lm_fin[others].any()
    assert int(jnp.max(fin.commit)) > int(jnp.max(st.commit))


def test_leader_crash_mid_lease_stays_linearizable():
    """Crash the leader while its lease is valid; revive it after the
    majority re-elected.  The revived row's lease has expired on the
    absolute tick clock and its term is stale, so it cannot serve reads
    from before the crash."""
    cfg = small_cfg(read_batch=2)
    st = _settled(cfg)
    ldr = int(np.argmax(np.asarray(leader_mask(st))))
    ticks = 60
    alive = np.ones((ticks, cfg.n), bool)
    alive[:25, ldr] = False
    fin, _ = run_schedule(st, cfg, jnp.zeros((ticks, cfg.n, cfg.n), bool),
                          jnp.asarray(alive), prop_count=2)
    assert int(check_state(fin, cfg)) == 0
    assert bool(jnp.all(fin.read_srv_idx >= fin.read_srv_goal))
    assert int(jnp.max(fin.commit)) > int(jnp.max(st.commit))


def test_invariant_flags_corrupted_serve():
    cfg = small_cfg(read_batch=2)
    st = _settled(cfg)
    fin, _ = run_ticks(st, cfg, 10, prop_count=2)
    assert int(check_state(fin, cfg)) == 0
    bad = dataclasses.replace(
        fin, read_srv_idx=fin.read_srv_goal - 1,
        read_srv_goal=jnp.maximum(fin.read_srv_goal, 1))
    assert int(check_state(bad, cfg)) & LINEARIZABLE_READ


def test_read_flight_events_recorded():
    cfg = small_cfg(read_batch=2, record_events=True, event_ring=128)
    st = _settled(cfg)
    fin, _ = run_ticks(st, cfg, 15, prop_count=2)
    from swarmkit_tpu.flightrec import decode_state
    events, _ = decode_state(fin)
    assert any(e.name == "READ_SERVED" for e in events)


@pytest.mark.slow
def test_dst_catches_stale_lease_read_mutation():
    """The detection self-test at unit size: the lease-disabled serve must
    trip LINEARIZABLE_READ (and only it) under the pinned-victim
    stale-leader adversary, while the stock kernel run of the same
    schedules stays clean (the 256-schedule version is the slow sweep).
    Slow-marked: seed-sensitive (has flaked at HEAD) and ~12s of wall."""
    from swarmkit_tpu import dst

    cfg = small_cfg(read_batch=2, seed=0)
    # the attack profiles in EXTRA_PROFILES trip their own safety/SLO
    # bits BY DESIGN against an undefended config (tests/test_threat_model.py
    # owns that coverage), and the storage profiles are pure no-ops with
    # the storage model off (tests/test_storage.py owns those) — sweeping
    # either here just dilutes the stale-leader lanes out of the
    # 12-schedule round-robin, so this self-test pins the read-path
    # mutation over the wire-only extras
    profiles = tuple(p for p in dst.EXTRA_PROFILES
                     if p not in dst.ATTACK_PROFILES
                     and p not in dst.STORAGE_PROFILES)
    batch, names = dst.make_batch(cfg, ticks=100, schedules=12, seed=0,
                                  profiles=profiles)
    res = dst.explore(init_state(cfg), cfg, batch, profiles=names,
                      prop_count=2, mutation="stale_lease_read")
    assert len(res.violating) > 0
    for s in res.violating:
        assert dst.bits_to_names(int(res.viol[s])) == ["linearizable_read"]


def test_stale_mutation_requires_read_path():
    from swarmkit_tpu.dst.explore import apply_mutation

    cfg = small_cfg()
    with pytest.raises(ValueError, match="read_batch"):
        apply_mutation(init_state(cfg), cfg, "stale_lease_read")


# ---------------------------------------------------------------------------
# bench wrapper (slow): the 99:1 read-mix config


@pytest.mark.slow
def test_bench_readmix_reads_dominate():
    """The acceptance bar for the read-heavy bench config: served reads/s
    at the 99:1 offered mix must be >= 10x committed entries/s."""
    import jax

    from bench import measure

    m = measure(jax, 256, 50_000, seed=7, election_tick=16,
                read_batch=99 * 2048 // 256)
    assert m["rate"] > 0
    assert m["read_rate"] >= 10 * m["rate"], m

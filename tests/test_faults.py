"""The shared fault surface (FaultSurface/FaultPlan) and its behavior on
the in-process and device-mesh wires: delay injection, crash_restart wire
purging, failure-count surfacing, and vote-health gating under partition.

Reference bar: manager/state/raft/testutils (partition/restart helpers)
and raft.go:1422 (health gating on votes); the gRPC-wire equivalents live
in tests/test_transport_health.py.
"""

import pytest

from swarmkit_tpu.api import Annotations, Node as ApiNode, NodeSpec
from swarmkit_tpu.raft.faults import FaultPlan, FaultSurface
from tests.conftest import async_test
from tests.node_harness import RaftHarness
from tests.test_device_transport import DeviceRaftHarness


def _obj(tag):
    return ApiNode(id=f"id-{tag}",
                   spec=NodeSpec(annotations=Annotations(name=f"o-{tag}")))


async def propose(node, tag):
    await node.store.update(lambda tx: tx.create(_obj(tag)))


def has_obj(node, tag):
    return node.store.get("node", f"id-{tag}") is not None


# --------------------------------------------------------------------------
# FaultSurface / FaultPlan unit semantics


def test_fault_surface_primitives():
    s = FaultSurface(seed=1)
    assert not s.faults_active()

    s.set_down("a")
    assert s._fault_blocked("b", "a")       # down blocks deliveries TO a
    assert not s._fault_blocked("a", "b")   # a can still send outward
    s.set_down("a", down=False)
    assert not s._fault_blocked("b", "a")

    s.partition(["a", "b"], ["c"])
    assert s._fault_blocked("a", "c") and s._fault_blocked("c", "b")
    assert not s._fault_blocked("a", "b")

    s.set_delay("a", "b", 2.5)
    assert s.delay_for("a", "b") == 2.5 and s.delay_for("b", "a") == 0.0
    s.set_drop("a", "b", 1.0)
    assert s.lossy("a", "b") and not s.lossy("b", "a")

    s.set_down("x")
    s.heal()   # clears partitions/drops/delays, NOT down (plans repair it)
    assert not s._fault_blocked("a", "c")
    assert s.delay_for("a", "b") == 0.0 and not s.lossy("a", "b")
    assert s._fault_blocked("a", "x")


def test_fault_plan_inject_and_repair():
    s = FaultSurface(seed=1)
    plan = FaultPlan.down("v")
    plan.inject(s)
    assert s._fault_blocked("a", "v")
    plan.heal(s)   # the down plan's repair un-downs the victim
    assert not s._fault_blocked("a", "v")

    split = FaultPlan.split(["v"], ["a", "b"])
    split.inject(s)
    assert s._fault_blocked("v", "a")
    split.heal(s)
    assert not s.faults_active()

    delay = FaultPlan.delay("a", "b", 1.5)
    delay.inject(s)
    assert s.delay_for("a", "b") == 1.5 and s.delay_for("b", "a") == 1.5
    delay.heal(s)
    assert not s.faults_active()


# --------------------------------------------------------------------------
# in-process wire


@async_test
async def test_delay_defers_replication_until_clock_advances():
    """An injected edge delay holds replication back deterministically:
    the follower only sees the entry once the fake clock passes the
    latency, and heal() restores immediate delivery."""
    h = RaftHarness()
    try:
        n1 = await h.add_node()
        await h.wait_for_leader()
        n2 = await h.add_node(join_from=n1)
        n3 = await h.add_node(join_from=n1)
        lead = await h.wait_for_cluster()

        FaultPlan.delay(lead.addr, n2.addr, 2.0).inject(h.network)
        FaultPlan.delay(lead.addr, n3.addr, 2.0).inject(h.network)
        import asyncio

        t = asyncio.ensure_future(propose(lead, "slow"))
        await h.pump(4)
        # delivery is parked on the clock: nobody has the entry yet
        assert not has_obj(n2, "slow") and not has_obj(n3, "slow")
        await h.wait_for(lambda: t.done() and has_obj(n2, "slow")
                         and has_obj(n3, "slow"))
        await t

        # heal clears the injected latency, but peer drains already parked
        # on the clock only wake on ticks — keep ticking while proposing
        h.network.heal()
        t2 = asyncio.ensure_future(propose(lead, "fast"))
        await h.wait_for(lambda: t2.done() and has_obj(n2, "fast")
                         and has_obj(n3, "fast"))
        await t2
    finally:
        await h.close()


@async_test
async def test_unreachable_peer_failure_counts_surface_in_status():
    """Consecutive delivery failures reach raft Node.status() through
    report_unreachable — as {count, last_failure} so probe-flip debugging
    can correlate against wall time — and clear once the peer is reachable
    again."""
    h = RaftHarness()
    try:
        n1 = await h.add_node()
        await h.wait_for_leader()
        n2 = await h.add_node(join_from=n1)
        n3 = await h.add_node(join_from=n1)
        lead = await h.wait_for_cluster()
        victim = n2 if lead is not n2 else n3

        h.network.set_down(victim.addr)
        await h.wait_for(lambda: lead.status()["peer_failures"].get(
            victim.raft_id, {"count": 0})["count"] >= 2)
        info = lead.status()["peer_failures"][victim.raft_id]
        assert info["last_failure"] <= lead.clock.now()

        h.network.set_down(victim.addr, down=False)
        await h.wait_for(lambda: victim.raft_id
                         not in lead.status()["peer_failures"])
    finally:
        await h.close()


# --------------------------------------------------------------------------
# device-mesh wire


@async_test
async def test_vote_gating_partition_device_mesh():
    """A partitioned minority must not win elections on the mailbox wire;
    the majority keeps committing, and heal() restores the victim."""
    h = DeviceRaftHarness()
    try:
        n1 = await h.add_node()
        await h.wait_for_leader()
        n2 = await h.add_node(join_from=n1)
        n3 = await h.add_node(join_from=n1)
        lead = await h.wait_for_cluster()
        victim = n2 if lead is not n2 else n3
        majority = [n for n in (n1, n2, n3) if n is not victim]

        FaultPlan.split(
            [victim.addr], [n.addr for n in majority]).inject(h.network)

        # several election timeouts: the isolated node campaigns but its
        # votes cannot cross the partition (wire block + vote-health gate)
        await h.tick(20)
        assert not victim.is_leader()
        lead = h.leader()
        assert lead is not None and lead in majority

        await propose(lead, "during")
        await h.wait_for(lambda: all(has_obj(n, "during") for n in majority))
        assert not has_obj(victim, "during")

        h.network.heal()
        lead = await h.wait_for_cluster()
        await h.wait_for(lambda: has_obj(victim, "during"))
        await propose(lead, "after")
        await h.wait_for(lambda: all(has_obj(n, "after")
                                     for n in (n1, n2, n3)))
    finally:
        await h.close()


@async_test
async def test_crash_restart_purges_staged_mailbox_entries():
    """crash_restart on the device wire kills payloads staged to/from the
    bounced address (the old incarnation's traffic), without breaking
    liveness for the cluster afterwards."""
    h = DeviceRaftHarness()
    try:
        n1 = await h.add_node()
        await h.wait_for_leader()
        n2 = await h.add_node(join_from=n1)
        n3 = await h.add_node(join_from=n1)
        lead = await h.wait_for_cluster()
        victim = n2 if lead is not n2 else n3

        # hold deliveries to the victim on the clock so they sit staged
        h.network.set_delay(lead.addr, victim.addr, 50.0)
        import asyncio

        t = asyncio.ensure_future(propose(lead, "wedged"))
        await h.pump(4)
        victim_row = h.network.row_for(victim.addr)
        assert any(victim_row in (frm, to) and q
                   for (frm, to), q in h.network._staged.items())

        FaultPlan.crash(victim.addr).inject(h.network)
        assert not any(victim_row in (frm, to) and q
                       for (frm, to), q in h.network._staged.items())

        h.network.heal()
        await h.wait_for(lambda: t.done() and has_obj(victim, "wedged"))
        await t
    finally:
        await h.close()

"""Fleet health plane (ISSUE 20): burn-rate engine, heat, e2e alerting.

Layers under test, host-side up:

- HeatTracker (multiraft/heat.py): delta/EWMA algebra, spill fusion,
  the hottest-first ranking contract.
- SloSpec / SLO_CATALOG (slo/spec.py): validation + page reachability.
- SloEngine (slo/engine.py): multi-window burn semantics on synthetic
  readings — window edges, partial windows, escalation, hysteresis,
  flapping suppression, transitions/alert records, metric publication
  and its group-cardinality gate.
- FleetSource (slo/source.py): which SLOs read from which device
  subsystems, and that dark subsystems yield ABSENT readings.
- End to end (the ISSUE 20 acceptance demo): a DST schedule degrading
  exactly one multi-raft group pages that group's SLOs within a bounded
  number of scrapes, heat ranks it hottest, and every untouched group
  stays ok with bit-identical state.
"""

import dataclasses

import jax
import numpy as np
import pytest

from swarmkit_tpu import multiraft
from swarmkit_tpu.dst.schedule import FaultSchedule
from swarmkit_tpu.metrics.registry import MetricsRegistry
from swarmkit_tpu.multiraft.heat import SPILL_WEIGHT, HeatTracker
from swarmkit_tpu.multiraft.obs import MultiRaftObs
from swarmkit_tpu.raft.sim.state import SimConfig
from swarmkit_tpu.slo import SLO_CATALOG, FleetSource, SloEngine, SloSpec

jnp = jax.numpy


# ---------------------------------------------------------------------------
# HeatTracker


class TestHeatTracker:
    def test_first_scrape_is_baseline(self):
        h = HeatTracker(3)
        heat = h.update(np.array([100, 200, 300]))
        assert (heat == 0).all()            # no delta yet, only a baseline

    def test_ewma_folds_commit_deltas(self):
        h = HeatTracker(2, alpha=0.5)
        h.update(np.array([0, 0]))
        heat = h.update(np.array([10, 40]))
        assert heat.tolist() == [5.0, 20.0]        # alpha * delta
        heat = h.update(np.array([20, 40]))        # +10 / +0
        assert heat.tolist() == [7.5, 10.0]        # EWMA decays idle group

    def test_spills_outweigh_commits(self):
        h = HeatTracker(2, alpha=1.0)
        h.update(np.array([0, 0]), np.array([0, 0]))
        # group 0: 8 commits; group 1: 2 commits + 2 spills
        heat = h.update(np.array([8, 2]), np.array([0, 2]))
        assert heat[1] == 2 + SPILL_WEIGHT * 2
        assert heat[1] > heat[0]            # saturation outranks throughput

    def test_rebaseline_on_decrease(self):
        h = HeatTracker(1, alpha=1.0)
        h.update(np.array([100]))
        heat = h.update(np.array([3]))      # fresh state: count in full
        assert heat[0] == 3.0

    def test_hottest_groups_stable_ties(self):
        h = HeatTracker(4, alpha=1.0)
        h.update(np.array([0, 0, 0, 0]))
        h.update(np.array([5, 9, 5, 1]))
        assert h.hottest_groups() == [1, 0, 2, 3]   # ties: lower index
        assert h.hottest_groups(2) == [1, 0]

    def test_shape_and_alpha_validation(self):
        with pytest.raises(ValueError):
            HeatTracker(2, alpha=0.0)
        h = HeatTracker(2)
        with pytest.raises(ValueError):
            h.update(np.zeros(3))


# ---------------------------------------------------------------------------
# SloSpec / catalog


class TestSloSpec:
    def test_validation(self):
        with pytest.raises(ValueError):
            SloSpec("x", "d", budget=0.0)
        with pytest.raises(ValueError):
            SloSpec("x", "d", budget=0.1, fast_window=5, slow_window=3)
        with pytest.raises(ValueError):
            SloSpec("x", "d", budget=0.1, warn_burn=4.0, page_burn=2.0)
        with pytest.raises(ValueError):
            SloSpec("x", "d", budget=0.1, clear_scrapes=0)

    def test_catalog_pages_are_reachable(self):
        """A threshold-style SLO reads one (bad, total) pair per scrape,
        capping its burn at 1/budget — every catalog entry must leave
        page_burn below that cap or `page` is dead configuration."""
        for spec in SLO_CATALOG:
            assert 1.0 / spec.budget > spec.page_burn, spec.name


# ---------------------------------------------------------------------------
# SloEngine burn-rate semantics (synthetic readings, no JAX)


def _spec(**kw):
    kw.setdefault("budget", 0.1)
    kw.setdefault("fast_window", 2)
    kw.setdefault("slow_window", 4)
    kw.setdefault("warn_burn", 2.0)
    kw.setdefault("page_burn", 6.0)
    kw.setdefault("clear_scrapes", 2)
    return SloSpec("t", "test objective", **kw)


def _engine(**kw):
    return SloEngine(catalog=(_spec(**kw),),
                     registry=MetricsRegistry(strict=True))


def _r(bad, total=1.0):
    return np.array([[bad, total]], np.float64)


class TestSloEngine:
    def test_page_requires_both_windows(self):
        """One catastrophic scrape maxes the fast window, but the slow
        window still averages it down — the group WARNS (slow burn 2.5
        clears warn_burn) yet cannot page until the slow window agrees.
        budget 0.1: frac 1.0 = burn 10."""
        eng = _engine(fast_window=1, slow_window=4)
        for _ in range(3):
            eng.observe({"t": _r(0.0)})
        fired = eng.observe({"t": _r(1.0)})     # fast burn 10, slow 2.5
        assert eng.state_of("t", 0) == "warn"
        assert [f["to"] for f in fired] == ["warn"]

    def test_partial_window_pages_early(self):
        """A fleet born into an outage pages on its very first scrape —
        windows evaluate over what's filled, not zero-padded."""
        eng = _engine()
        fired = eng.observe({"t": _r(1.0)})
        assert eng.state_of("t", 0) == "page"
        assert [f["to"] for f in fired] == ["page"]
        assert eng.observe({"t": _r(1.0)}) == []    # staying paged is quiet

    def test_warn_level_between_thresholds(self):
        eng = _engine()
        for _ in range(4):
            eng.observe({"t": _r(0.3)})         # burn 3: warn < 3 < page
        assert eng.state_of("t", 0) == "warn"

    def test_hysteresis_steps_down_one_level(self):
        eng = _engine()
        for _ in range(4):
            eng.observe({"t": _r(1.0)})
        assert eng.state_of("t", 0) == "page"
        states = []
        for _ in range(8):
            eng.observe({"t": _r(0.0)})
            states.append(eng.state_of("t", 0))
        # burn decays below warn_burn only after the bad scrapes leave
        # the slow window (disagreeing windows hold state, not calm);
        # then each clear_scrapes=2 calm run steps down ONE level
        assert states[-1] == "ok"
        assert "warn" in states                 # never page -> ok directly

    def test_flapping_suppression_resets_calm(self):
        """An oscillating group must not de-escalate: any non-calm
        scrape resets the consecutive-calm counter."""
        eng = _engine(fast_window=1, slow_window=2, clear_scrapes=3)
        for _ in range(3):
            eng.observe({"t": _r(1.0)})
        assert eng.state_of("t", 0) == "page"
        for _ in range(4):                      # calm, calm, BAD, calm...
            eng.observe({"t": _r(0.0)})
            eng.observe({"t": _r(0.0)})
            eng.observe({"t": _r(1.0)})
        assert eng.state_of("t", 0) == "page"   # never 3 calm in a row

    def test_transitions_and_alert_records(self):
        reg = MetricsRegistry(strict=True)
        eng = SloEngine(catalog=(_spec(),), registry=reg)
        for _ in range(2):
            eng.observe({"t": _r(1.0)})
        for _ in range(8):
            eng.observe({"t": _r(0.0)})
        recs = list(eng.alerts)
        assert [(r["from"], r["to"]) for r in recs] == \
            [("ok", "page"), ("page", "warn"), ("warn", "ok")]
        assert all(r["slo"] == "t" and r["group"] == 0 for r in recs)
        snap = reg.snapshot()
        trans = snap["swarm_slo_transitions_total"]
        assert trans["slo=t,group=0,state=page"] == 1
        assert trans["slo=t,group=0,state=ok"] == 1

    def test_active_ranks_pages_first(self):
        eng = SloEngine(
            catalog=(_spec(), dataclasses.replace(_spec(), name="u")),
            registry=MetricsRegistry(strict=True))
        for _ in range(2):
            eng.observe({"t": _r(0.3), "u": _r(1.0)})
        active = eng.active()
        assert [(a["slo"], a["state"]) for a in active] == \
            [("u", "page"), ("t", "warn")]

    def test_unknown_slo_and_bad_shape_raise(self):
        eng = _engine()
        with pytest.raises(KeyError):
            eng.observe({"bogus": _r(0.0)})
        with pytest.raises(ValueError):
            eng.observe({"t": np.zeros((2, 3))})

    def test_per_group_metrics_gate_on_cardinality(self):
        from swarmkit_tpu.slo.engine import GROUP_LABEL_CAP
        reg = MetricsRegistry(strict=True)
        eng = SloEngine(catalog=(_spec(),), registry=reg)
        big = np.tile([[1.0, 1.0]], (GROUP_LABEL_CAP + 1, 1))
        for _ in range(2):
            eng.observe({"t": big})
        # evaluation ran (every group paged), publication was gated
        assert eng.state_of("t", GROUP_LABEL_CAP) == "page"
        assert reg.snapshot()["swarm_slo_state"] == {}


# ---------------------------------------------------------------------------
# FleetSource wiring


CFG = SimConfig(n=5, log_len=96, window=16, apply_batch=16, max_props=8,
                keep=8, seed=7, election_tick=10, collect_stats=True,
                read_batch=4, read_leases=True, collect_telemetry=True,
                telemetry_prop_ring=64)


class TestFleetSource:
    """Scrape-boundary semantics of the device->SLO adapter.  Each test
    compiles fresh 2-group programs, so the class is slow-marked for the
    tier-1 wall budget; the end-to-end alert demo below keeps FleetSource
    covered in tier-1."""

    @pytest.mark.slow
    def test_reading_presence_tracks_subsystems(self):
        gs = multiraft.init_groups(CFG, 2)
        gs, _ = multiraft.run_group_ticks(gs, CFG, 40, prop_count=2)
        src = FleetSource(CFG)
        first = src.scrape(gs)
        # telemetry + read path on; no router, no storage model, and the
        # first scrape only baselines the leader diff
        assert sorted(first) == ["commit_p99", "read_block_ratio"]
        gs, _ = multiraft.run_group_ticks(gs, CFG, 20, prop_count=2)
        second = src.scrape(gs)
        assert sorted(second) == ["commit_p99", "leader_churn",
                                  "read_block_ratio"]
        for arr in second.values():
            assert arr.shape == (2, 2)
            assert (arr[:, 0] <= arr[:, 1]).all()   # bad <= total
        # steady elected state: commits flowed, nothing above threshold
        assert second["commit_p99"][:, 1].sum() > 0
        assert second["leader_churn"][:, 0].sum() == 0

    @pytest.mark.slow
    def test_dark_subsystems_absent(self):
        cfg = dataclasses.replace(CFG, collect_telemetry=False,
                                  telemetry_prop_ring=0, read_batch=0,
                                  read_leases=False)
        gs = multiraft.init_groups(cfg, 2)
        gs, _ = multiraft.run_group_ticks(gs, cfg, 30, prop_count=2)
        src = FleetSource(cfg)
        src.scrape(gs)
        out = src.scrape(gs)
        assert sorted(out) == ["leader_churn"]

    @pytest.mark.slow
    def test_router_spills_feed_spill_ratio(self):
        gs = multiraft.init_groups(CFG, 2)
        gs, _ = multiraft.run_group_ticks(gs, CFG, 40, prop_count=0)
        r = multiraft.Router(CFG, groups=2)
        src = FleetSource(CFG)
        src.scrape(gs, router=r)                 # baseline
        for i in range(64):                      # 4x the per-flush capacity
            r.offer(f"k{i}", i)
        gs = r.flush(gs)
        out = src.scrape(gs, router=r)
        spills = out["spill_ratio"]
        assert spills[:, 0].sum() > 0
        assert (spills[:, 0] <= spills[:, 1]).all()


# ---------------------------------------------------------------------------
# end-to-end: the ISSUE 20 acceptance demo


def _flood_churn_schedule(groups, ticks, n, victim):
    """Degrade ONLY `victim`: a standing append flood (the heat signal)
    plus a leader partition window late in every 25-tick chunk (the
    churn signal — the window ends close enough to the scrape boundary
    that the post-recovery leader differs from the previous scrape's)."""
    drop = np.zeros((groups, ticks, n, n), bool)
    alive = np.ones((groups, ticks, n), bool)
    tl = np.zeros((groups, ticks), bool)
    cc = np.zeros((groups, ticks), bool)
    flood = np.zeros((groups, ticks), bool)
    flood[victim, 10:] = True
    for start in range(0, ticks, 25):
        tl[victim, start + 8:start + 21] = True
    return FaultSchedule(drop=jnp.asarray(drop), alive=jnp.asarray(alive),
                         target_leader=jnp.asarray(tl),
                         crash_campaign=jnp.asarray(cc),
                         append_flood=jnp.asarray(flood))


def _slice_ticks(schedule, t0, t1):
    return jax.tree_util.tree_map(lambda a: a[:, t0:t1], schedule)


class TestEndToEndAlert:
    def test_one_degraded_group_pages_and_ranks_hottest(self):
        groups, victim, chunk, chunks = 4, 2, 25, 10
        ticks = chunk * chunks
        g0 = multiraft.init_groups(CFG, groups)
        g0, _ = multiraft.run_group_ticks(g0, CFG, 60)   # elect the fleet

        faulty_sched = _flood_churn_schedule(groups, ticks, CFG.n, victim)
        quiet_sched = dataclasses.replace(
            faulty_sched,
            target_leader=jnp.zeros((groups, ticks), bool),
            append_flood=jnp.zeros((groups, ticks), bool))

        reg = MetricsRegistry(strict=True)
        obs = MultiRaftObs(registry=reg)
        src = FleetSource(CFG)
        eng = SloEngine(registry=reg)
        obs.publish(g0)
        eng.observe(src.scrape(g0))              # scrape 1: baselines

        paged_at = None
        faulty, quiet = g0, g0
        for c in range(chunks):
            sl = _slice_ticks(faulty_sched, c * chunk, (c + 1) * chunk)
            faulty, viol, _ = multiraft.run_groups_under_schedule(
                faulty, CFG, sl, prop_count=2)
            assert not int(np.asarray(viol).sum())
            sl = _slice_ticks(quiet_sched, c * chunk, (c + 1) * chunk)
            quiet, qviol, _ = multiraft.run_groups_under_schedule(
                quiet, CFG, sl, prop_count=2)
            assert not int(np.asarray(qviol).sum())
            obs.publish(faulty)
            eng.observe(src.scrape(faulty))
            if paged_at is None and any(
                    a["group"] == victim and a["state"] == "page"
                    for a in eng.active()):
                paged_at = c + 2                 # + the baseline scrape

        # 1. the victim PAGED within a bounded number of scrapes
        assert paged_at is not None and paged_at <= 8, \
            f"victim never paged; active={eng.active()}, " \
            f"alerts={list(eng.alerts)}"
        assert eng.state_of("leader_churn", victim) == "page"

        # 2. every untouched group stays ok on every SLO
        for a in eng.active():
            assert a["group"] == victim, f"bystander alerted: {a}"

        # 3. heat ranks the flooded group hottest (flood commits ride
        #    the victim's commit rate), and the gauge published
        assert obs.hottest_groups()[0] == victim
        heat_rows = reg.snapshot()["swarm_multiraft_group_heat"]
        assert heat_rows[f"group={victim}"] == max(heat_rows.values())

        # 4. fault isolation: untouched groups are bit-identical to the
        #    quiet run of the same driver program
        for g in range(groups):
            if g == victim:
                continue
            a = multiraft.slice_group(quiet, g)
            b = multiraft.slice_group(faulty, g)
            for la, lb in zip(jax.tree_util.tree_leaves(a),
                              jax.tree_util.tree_leaves(b)):
                np.testing.assert_array_equal(np.asarray(la),
                                              np.asarray(lb))

        # 5. the alert trail names the victim's escalation explicitly
        churn = [r for r in eng.alerts if r["slo"] == "leader_churn"
                 and r["group"] == victim and r["to"] == "page"]
        assert churn and churn[0]["fast_burn"] >= 6.0

"""Allocator tests (reference: manager/allocator/allocator_test.go)."""

import asyncio

import pytest

from swarmkit_tpu.api import (
    Annotations, ContainerSpec, Network, NetworkSpec, ReplicatedService,
    Service, ServiceSpec, TaskSpec, TaskState,
)
from swarmkit_tpu.api.types import EndpointSpecRef, PortConfig
from swarmkit_tpu.manager.allocator import Allocator, DYNAMIC_PORT_START
from swarmkit_tpu.manager.orchestrator import common
from swarmkit_tpu.store.memory import MemoryStore
from swarmkit_tpu.utils.clock import FakeClock
from tests.conftest import async_test


async def pump(clock, steps=12):
    for _ in range(steps):
        await asyncio.sleep(0)
    await clock.advance(0.1)
    for _ in range(steps):
        await asyncio.sleep(0)


def make_service(name="web", ports=None, networks=None):
    spec = ServiceSpec(
        annotations=Annotations(name=name),
        task=TaskSpec(container=ContainerSpec(image="img")),
        replicated=ReplicatedService(replicas=1),
        networks=networks or [])
    if ports:
        spec.endpoint = EndpointSpecRef(ports=ports)
    return Service(id=f"svc-{name}", spec=spec)


@async_test
async def test_network_subnet_allocation():
    clock = FakeClock()
    store = MemoryStore(clock=clock.now)
    alloc = Allocator(store, clock=clock)
    await alloc.start()
    net = Network(id="net1",
                  spec=NetworkSpec(annotations=Annotations(name="overlay1")))
    await store.update(lambda tx: tx.create(net))
    await pump(clock)
    n = store.get("network", "net1")
    assert n.ipam is not None and n.ipam.configs[0].subnet == "10.1.0.0/24"
    assert n.ipam.configs[0].gateway == "10.1.0.1"
    await alloc.stop()


@async_test
async def test_service_port_and_vip_allocation():
    clock = FakeClock()
    store = MemoryStore(clock=clock.now)
    alloc = Allocator(store, clock=clock)
    await alloc.start()
    net = Network(id="net1",
                  spec=NetworkSpec(annotations=Annotations(name="overlay1")))
    svc = make_service(ports=[
        PortConfig(protocol="tcp", target_port=80, published_port=8080),
        PortConfig(protocol="tcp", target_port=443)],  # dynamic
        networks=["net1"])
    await store.update(lambda tx: (tx.create(net), tx.create(svc)))
    await pump(clock)
    s = store.get("service", svc.id)
    assert s.endpoint is not None
    ports = {p.target_port: p.published_port for p in s.endpoint.ports}
    assert ports[80] == 8080
    assert ports[443] >= DYNAMIC_PORT_START
    vips = [v for v in s.endpoint.virtual_ips if v.network_id == "net1"]
    assert len(vips) == 1 and vips[0].addr.startswith("10.1.0.")
    await alloc.stop()


@async_test
async def test_task_new_to_pending_with_attachments():
    clock = FakeClock()
    store = MemoryStore(clock=clock.now)
    alloc = Allocator(store, clock=clock)
    await alloc.start()
    net = Network(id="net1",
                  spec=NetworkSpec(annotations=Annotations(name="overlay1")))
    svc = make_service(networks=["net1"])
    task = common.new_task(None, svc, slot=1)
    await store.update(lambda tx: (tx.create(net), tx.create(svc),
                                   tx.create(task)))
    await pump(clock)
    await pump(clock)
    t = store.get("task", task.id)
    assert t.status.state == TaskState.PENDING
    assert len(t.networks) == 1 and t.networks[0].network_id == "net1"
    assert t.networks[0].addresses[0].startswith("10.1.0.")
    # distinct address from the service VIP
    await alloc.stop()


@async_test
async def test_restore_does_not_double_allocate():
    clock = FakeClock()
    store = MemoryStore(clock=clock.now)
    alloc = Allocator(store, clock=clock)
    await alloc.start()
    svc = make_service(ports=[PortConfig(protocol="tcp", target_port=80)])
    await store.update(lambda tx: tx.create(svc))
    await pump(clock)
    first = store.get("service", svc.id).endpoint.ports[0].published_port
    await alloc.stop()

    # a fresh allocator over the same store must keep the allocation
    alloc2 = Allocator(store, clock=clock)
    await alloc2.start()
    svc2 = make_service(name="other",
                        ports=[PortConfig(protocol="tcp", target_port=80)])
    await store.update(lambda tx: tx.create(svc2))
    await pump(clock)
    second = store.get("service", svc2.id).endpoint.ports[0].published_port
    assert store.get("service", svc.id).endpoint.ports[0].published_port \
        == first
    assert second != first
    await alloc2.stop()


@async_test
async def test_endpoint_update_releases_and_swaps_ports():
    """Regression: ports dropped/changed by a spec update must be released
    so another service (or the same one) can claim them."""
    clock = FakeClock()
    store = MemoryStore(clock=clock.now)
    alloc = Allocator(store, clock=clock)
    await alloc.start()
    svc = make_service(ports=[PortConfig(protocol="tcp", target_port=80,
                                         published_port=8080,
                                         publish_mode="ingress")])
    await store.update(lambda tx: tx.create(svc))
    await pump(clock)
    assert store.get("service", svc.id).endpoint.ports[0].published_port == 8080

    # swap 8080 -> 9090
    s = store.get("service", svc.id)
    s.spec.endpoint = EndpointSpecRef(ports=[
        PortConfig(protocol="tcp", target_port=80, published_port=9090,
                   publish_mode="ingress")])
    await store.update(lambda tx: tx.update(s))
    await pump(clock)
    assert store.get("service", svc.id).endpoint.ports[0].published_port == 9090

    # 8080 must be claimable again by a second service
    svc2 = make_service(name="web2",
                        ports=[PortConfig(protocol="tcp", target_port=81,
                                          published_port=8080,
                                          publish_mode="ingress")])
    await store.update(lambda tx: tx.create(svc2))
    await pump(clock)
    assert store.get("service", svc2.id).endpoint.ports[0].published_port == 8080
    await alloc.stop()


@async_test
async def test_endpoint_dynamic_to_explicit_port_change():
    clock = FakeClock()
    store = MemoryStore(clock=clock.now)
    alloc = Allocator(store, clock=clock)
    await alloc.start()
    svc = make_service(ports=[PortConfig(protocol="tcp", target_port=80,
                                         publish_mode="ingress")])
    await store.update(lambda tx: tx.create(svc))
    await pump(clock)
    dyn = store.get("service", svc.id).endpoint.ports[0].published_port
    assert dyn >= DYNAMIC_PORT_START

    s = store.get("service", svc.id)
    s.spec.endpoint = EndpointSpecRef(ports=[
        PortConfig(protocol="tcp", target_port=80, published_port=7777,
                   publish_mode="ingress")])
    await store.update(lambda tx: tx.update(s))
    await pump(clock)
    assert store.get("service", svc.id).endpoint.ports[0].published_port == 7777
    # the old dynamic port is free again
    assert dyn not in alloc.ports._space("tcp").master
    await alloc.stop()


def test_port_spaces_are_per_protocol():
    """tcp/udp/sctp have independent port spaces (reference:
    portallocator.go portSpaces map): the same number can be published on
    every protocol, and dynamic cursors don't interfere."""
    from swarmkit_tpu.manager.allocator import PortAllocator, PortConflict

    pa = PortAllocator()
    assert pa.allocate("tcp", 8080) == 8080
    assert pa.allocate("udp", 8080) == 8080   # different space: no conflict
    assert pa.allocate("sctp", 8080) == 8080
    with pytest.raises(PortConflict):
        pa.allocate("tcp", 8080)
    # dynamic allocations start at the same base per protocol
    assert pa.allocate("tcp") == 30000
    assert pa.allocate("udp") == 30000


def test_dynamic_port_space_wraps_after_release():
    """Released dynamic ports become reusable once the cursor wraps
    (reference: idm bitmask reuse; the round-3 allocator leaked them
    permanently)."""
    from swarmkit_tpu.manager.allocator import (
        DYNAMIC_PORT_END, DYNAMIC_PORT_START, PortAllocator, PortConflict,
    )

    pa = PortAllocator()
    span = DYNAMIC_PORT_END - DYNAMIC_PORT_START + 1
    for _ in range(span):
        pa.allocate("tcp")
    with pytest.raises(PortConflict):
        pa.allocate("tcp")
    pa.release("tcp", 31000)
    assert pa.allocate("tcp") == 31000   # wraps and finds the hole


@async_test
async def test_host_mode_port_not_in_cluster_space():
    """Host-mode published ports are per-node and never consume the
    cluster ingress space (api/types.proto:633 PublishMode; reference
    allocatePorts skips non-ingress)."""
    clock = FakeClock()
    store = MemoryStore(clock=clock.now)
    alloc = Allocator(store, clock=clock)
    await alloc.start()
    try:
        svc = make_service(name="hostsvc", ports=[
            PortConfig(protocol="tcp", target_port=80, published_port=8080,
                       publish_mode="host")])
        await store.update(lambda tx: tx.create(svc))
        await pump(clock)
        ep = store.get("service", svc.id).endpoint
        assert ep.ports[0].published_port == 8080
        assert ep.ports[0].publish_mode == "host"
        # the cluster ingress space still has 8080 free: an ingress
        # service can publish the same number
        svc2 = make_service(name="ingsvc", ports=[
            PortConfig(protocol="tcp", target_port=81, published_port=8080,
                       publish_mode="ingress")])
        await store.update(lambda tx: tx.create(svc2))
        await pump(clock)
        ep2 = store.get("service", svc2.id).endpoint
        assert ep2.ports[0].published_port == 8080
    finally:
        await alloc.stop()


@async_test
async def test_user_subnet_pool_honored_and_grows():
    """NetworkSpec.ipam subnets are used as configured (cnmallocator IPAM
    options); when a small pool fills, the allocator GROWS the network
    with a fresh auto subnet persisted on the record (round-3 weak #6:
    one /24 capped everything at 253 addresses)."""
    from swarmkit_tpu.api import Task, TaskStatus
    from swarmkit_tpu.api.types import IPAMConfig, IPAMOptions

    clock = FakeClock()
    store = MemoryStore(clock=clock.now)
    alloc = Allocator(store, clock=clock)
    await alloc.start()
    try:
        net = Network(id="tiny-net", spec=NetworkSpec(
            annotations=Annotations(name="tiny"),
            ipam=IPAMOptions(configs=[
                IPAMConfig(subnet="192.168.7.0/29")])))
        await store.update(lambda tx: tx.create(net))
        await pump(clock)
        rec = store.get("network", "tiny-net")
        assert rec.ipam.configs[0].subnet == "192.168.7.0/29"
        assert rec.ipam.configs[0].gateway == "192.168.7.1"

        # a /29 holds 5 usable task addresses (8 - network - gateway -
        # broadcast); the 6th allocation must grow the pool
        for i in range(7):
            t = Task(id=f"t{i}", spec=TaskSpec(networks=["tiny-net"]),
                     status=TaskStatus(state=TaskState.NEW),
                     desired_state=int(TaskState.RUNNING))
            await store.update(lambda tx, t=t: tx.create(t))
        await pump(clock)
        await pump(clock)
        tasks = [store.get("task", f"t{i}") for i in range(7)]
        addrs = [t.networks[0].addresses[0] for t in tasks if t.networks]
        assert len(addrs) == 7, "growth did not keep allocating"
        assert len(set(addrs)) == 7
        in_pool = [a for a in addrs if a.startswith("192.168.7.")]
        grown = [a for a in addrs if a.startswith("10.")]
        assert len(in_pool) == 5 and len(grown) == 2, addrs
        rec = store.get("network", "tiny-net")
        assert len(rec.ipam.configs) == 2, "grown subnet not persisted"
    finally:
        await alloc.stop()


def test_user_subnet_normalized_to_network_base():
    """A spec subnet with host bits set (10.5.0.7/24) is the 10.5.0.0/24
    network: gateway .1, first host .2 (advisor round-4 finding; the
    reference's net.ParseCIDR masks the same way)."""
    from swarmkit_tpu.manager.allocator import IPAM, _gateway

    assert _gateway("10.5.0.7/24") == "10.5.0.1"
    assert _gateway("192.168.7.128/25") == "192.168.7.129"
    ipam = IPAM()
    ipam.allocate_subnet("net1", "10.5.0.7/24")
    addr = ipam.allocate_address("net1")
    assert addr.startswith("10.5.0."), addr
    host = int(addr.split("/")[0].split(".")[-1])
    assert host >= 2


def test_auto_pools_skip_user_subnet_overlap():
    """Auto 10.<n>.0.0/24 pools must not collide with user-configured
    subnets, and overlapping user subnets are rejected."""
    import pytest

    from swarmkit_tpu.manager.allocator import IPAM

    ipam = IPAM()
    ipam.allocate_subnet("usernet", "10.1.0.0/16")   # covers 10.1.*.*
    auto = ipam.allocate_subnet("othernet")          # must skip 10.1.0.0/24
    assert not auto.startswith("10.1."), auto
    with pytest.raises(ValueError, match="overlaps"):
        ipam.allocate_subnet("third", "10.1.4.0/24")


@async_test
async def test_bad_user_subnet_does_not_kill_allocator_loop():
    """An overlapping/bad spec subnet fails THAT network's allocation only;
    the allocator keeps serving other networks (code-review round-5
    finding: a raised ValueError used to crash the allocator actor)."""
    from swarmkit_tpu.api.types import IPAMConfig, IPAMOptions

    clock = FakeClock()
    store = MemoryStore(clock=clock.now)
    alloc = Allocator(store, clock=clock)
    await alloc.start()
    try:
        good1 = Network(id="n-base", spec=NetworkSpec(
            annotations=Annotations(name="base"),
            ipam=IPAMOptions(configs=[IPAMConfig(subnet="10.9.0.0/16")])))
        bad = Network(id="n-bad", spec=NetworkSpec(
            annotations=Annotations(name="bad"),
            ipam=IPAMOptions(configs=[IPAMConfig(subnet="10.9.4.0/24")])))
        good2 = Network(id="n-after", spec=NetworkSpec(
            annotations=Annotations(name="after")))
        for n in (good1, bad, good2):
            await store.update(lambda tx, n=n: tx.create(n))
        await pump(clock)
        await pump(clock)
        assert store.get("network", "n-base").ipam is not None
        # the bad one stays unallocated, the loop stays alive, and the
        # network created after it still allocates
        assert store.get("network", "n-bad").ipam is None
        assert store.get("network", "n-after").ipam is not None
    finally:
        await alloc.stop()


@async_test
async def test_network_removal_releases_subnets_for_reuse():
    """Removing a network frees its IPAM pools: re-creating a network with
    the same subnet succeeds, and a partially overlapping multi-subnet
    request leaks nothing when rejected (code-review round-5 findings)."""
    from swarmkit_tpu.api.types import IPAMConfig, IPAMOptions

    clock = FakeClock()
    store = MemoryStore(clock=clock.now)
    alloc = Allocator(store, clock=clock)
    await alloc.start()
    try:
        n1 = Network(id="nA", spec=NetworkSpec(
            annotations=Annotations(name="a"),
            ipam=IPAMOptions(configs=[IPAMConfig(subnet="10.7.0.0/24")])))
        await store.update(lambda tx: tx.create(n1))
        await pump(clock)
        assert store.get("network", "nA").ipam is not None

        await store.update(lambda tx: tx.delete("network", "nA"))
        await pump(clock)
        n2 = Network(id="nB", spec=NetworkSpec(
            annotations=Annotations(name="b"),
            ipam=IPAMOptions(configs=[IPAMConfig(subnet="10.7.0.0/24")])))
        await store.update(lambda tx: tx.create(n2))
        await pump(clock)
        rec = store.get("network", "nB")
        assert rec.ipam is not None, "freed subnet was not reusable"
        assert rec.ipam.configs[0].subnet == "10.7.0.0/24"

        # atomic multi-subnet: second subnet overlaps nB -> NOTHING leaks
        bad = Network(id="nC", spec=NetworkSpec(
            annotations=Annotations(name="c"),
            ipam=IPAMOptions(configs=[IPAMConfig(subnet="10.8.0.0/24"),
                                      IPAMConfig(subnet="10.7.0.0/24")])))
        await store.update(lambda tx: tx.create(bad))
        await pump(clock)
        assert store.get("network", "nC").ipam is None
        # the non-overlapping first subnet must NOT be held by nC's
        # failed attempt
        good = Network(id="nD", spec=NetworkSpec(
            annotations=Annotations(name="d"),
            ipam=IPAMOptions(configs=[IPAMConfig(subnet="10.8.0.0/24")])))
        await store.update(lambda tx: tx.create(good))
        await pump(clock)
        assert store.get("network", "nD").ipam is not None, \
            "rejected multi-subnet attempt leaked a pool"
    finally:
        await alloc.stop()

"""Allocator tests (reference: manager/allocator/allocator_test.go)."""

import asyncio

from swarmkit_tpu.api import (
    Annotations, ContainerSpec, Network, NetworkSpec, ReplicatedService,
    Service, ServiceSpec, TaskSpec, TaskState,
)
from swarmkit_tpu.api.types import EndpointSpecRef, PortConfig
from swarmkit_tpu.manager.allocator import Allocator, DYNAMIC_PORT_START
from swarmkit_tpu.manager.orchestrator import common
from swarmkit_tpu.store.memory import MemoryStore
from swarmkit_tpu.utils.clock import FakeClock
from tests.conftest import async_test


async def pump(clock, steps=12):
    for _ in range(steps):
        await asyncio.sleep(0)
    await clock.advance(0.1)
    for _ in range(steps):
        await asyncio.sleep(0)


def make_service(name="web", ports=None, networks=None):
    spec = ServiceSpec(
        annotations=Annotations(name=name),
        task=TaskSpec(container=ContainerSpec(image="img")),
        replicated=ReplicatedService(replicas=1),
        networks=networks or [])
    if ports:
        spec.endpoint = EndpointSpecRef(ports=ports)
    return Service(id=f"svc-{name}", spec=spec)


@async_test
async def test_network_subnet_allocation():
    clock = FakeClock()
    store = MemoryStore(clock=clock.now)
    alloc = Allocator(store, clock=clock)
    await alloc.start()
    net = Network(id="net1",
                  spec=NetworkSpec(annotations=Annotations(name="overlay1")))
    await store.update(lambda tx: tx.create(net))
    await pump(clock)
    n = store.get("network", "net1")
    assert n.ipam is not None and n.ipam.configs[0].subnet == "10.1.0.0/24"
    assert n.ipam.configs[0].gateway == "10.1.0.1"
    await alloc.stop()


@async_test
async def test_service_port_and_vip_allocation():
    clock = FakeClock()
    store = MemoryStore(clock=clock.now)
    alloc = Allocator(store, clock=clock)
    await alloc.start()
    net = Network(id="net1",
                  spec=NetworkSpec(annotations=Annotations(name="overlay1")))
    svc = make_service(ports=[
        PortConfig(protocol="tcp", target_port=80, published_port=8080),
        PortConfig(protocol="tcp", target_port=443)],  # dynamic
        networks=["net1"])
    await store.update(lambda tx: (tx.create(net), tx.create(svc)))
    await pump(clock)
    s = store.get("service", svc.id)
    assert s.endpoint is not None
    ports = {p.target_port: p.published_port for p in s.endpoint.ports}
    assert ports[80] == 8080
    assert ports[443] >= DYNAMIC_PORT_START
    vips = [v for v in s.endpoint.virtual_ips if v.network_id == "net1"]
    assert len(vips) == 1 and vips[0].addr.startswith("10.1.0.")
    await alloc.stop()


@async_test
async def test_task_new_to_pending_with_attachments():
    clock = FakeClock()
    store = MemoryStore(clock=clock.now)
    alloc = Allocator(store, clock=clock)
    await alloc.start()
    net = Network(id="net1",
                  spec=NetworkSpec(annotations=Annotations(name="overlay1")))
    svc = make_service(networks=["net1"])
    task = common.new_task(None, svc, slot=1)
    await store.update(lambda tx: (tx.create(net), tx.create(svc),
                                   tx.create(task)))
    await pump(clock)
    await pump(clock)
    t = store.get("task", task.id)
    assert t.status.state == TaskState.PENDING
    assert len(t.networks) == 1 and t.networks[0].network_id == "net1"
    assert t.networks[0].addresses[0].startswith("10.1.0.")
    # distinct address from the service VIP
    await alloc.stop()


@async_test
async def test_restore_does_not_double_allocate():
    clock = FakeClock()
    store = MemoryStore(clock=clock.now)
    alloc = Allocator(store, clock=clock)
    await alloc.start()
    svc = make_service(ports=[PortConfig(protocol="tcp", target_port=80)])
    await store.update(lambda tx: tx.create(svc))
    await pump(clock)
    first = store.get("service", svc.id).endpoint.ports[0].published_port
    await alloc.stop()

    # a fresh allocator over the same store must keep the allocation
    alloc2 = Allocator(store, clock=clock)
    await alloc2.start()
    svc2 = make_service(name="other",
                        ports=[PortConfig(protocol="tcp", target_port=80)])
    await store.update(lambda tx: tx.create(svc2))
    await pump(clock)
    second = store.get("service", svc2.id).endpoint.ports[0].published_port
    assert store.get("service", svc.id).endpoint.ports[0].published_port \
        == first
    assert second != first
    await alloc2.stop()


@async_test
async def test_endpoint_update_releases_and_swaps_ports():
    """Regression: ports dropped/changed by a spec update must be released
    so another service (or the same one) can claim them."""
    clock = FakeClock()
    store = MemoryStore(clock=clock.now)
    alloc = Allocator(store, clock=clock)
    await alloc.start()
    svc = make_service(ports=[PortConfig(protocol="tcp", target_port=80,
                                         published_port=8080,
                                         publish_mode="ingress")])
    await store.update(lambda tx: tx.create(svc))
    await pump(clock)
    assert store.get("service", svc.id).endpoint.ports[0].published_port == 8080

    # swap 8080 -> 9090
    s = store.get("service", svc.id)
    s.spec.endpoint = EndpointSpecRef(ports=[
        PortConfig(protocol="tcp", target_port=80, published_port=9090,
                   publish_mode="ingress")])
    await store.update(lambda tx: tx.update(s))
    await pump(clock)
    assert store.get("service", svc.id).endpoint.ports[0].published_port == 9090

    # 8080 must be claimable again by a second service
    svc2 = make_service(name="web2",
                        ports=[PortConfig(protocol="tcp", target_port=81,
                                          published_port=8080,
                                          publish_mode="ingress")])
    await store.update(lambda tx: tx.create(svc2))
    await pump(clock)
    assert store.get("service", svc2.id).endpoint.ports[0].published_port == 8080
    await alloc.stop()


@async_test
async def test_endpoint_dynamic_to_explicit_port_change():
    clock = FakeClock()
    store = MemoryStore(clock=clock.now)
    alloc = Allocator(store, clock=clock)
    await alloc.start()
    svc = make_service(ports=[PortConfig(protocol="tcp", target_port=80,
                                         publish_mode="ingress")])
    await store.update(lambda tx: tx.create(svc))
    await pump(clock)
    dyn = store.get("service", svc.id).endpoint.ports[0].published_port
    assert dyn >= DYNAMIC_PORT_START

    s = store.get("service", svc.id)
    s.spec.endpoint = EndpointSpecRef(ports=[
        PortConfig(protocol="tcp", target_port=80, published_port=7777,
                   publish_mode="ingress")])
    await store.update(lambda tx: tx.update(s))
    await pump(clock)
    assert store.get("service", svc.id).endpoint.ports[0].published_port == 7777
    # the old dynamic port is free again
    assert (("tcp", dyn)) not in alloc.ports._allocated
    await alloc.stop()

"""CLI + support-lib tests: swarmd/swarmctl socket round trip, template
expansion, rafttool dumps.

Reference scenarios: cmd/swarmctl usage, template/expand_test.go,
cmd/swarm-rafttool/dump.go.
"""

import asyncio
import io
import json
import os
import tempfile

import pytest

from swarmkit_tpu.api import Annotations, Task, TaskSpec, TaskState
from swarmkit_tpu.api.objects import Node as ApiNode
from swarmkit_tpu.api.specs import ContainerSpec
from swarmkit_tpu.api.types import NodeDescription, Platform
from swarmkit_tpu.template import (
    TemplateError, expand, expand_container_spec, task_context,
)
from tests.conftest import async_test, requires_cryptography


def test_template_expansion():
    from swarmkit_tpu.api.specs import Mount

    task = Task(id="t1", service_id="s1", slot=3, spec=TaskSpec(
        container=ContainerSpec(
            image="img",
            env=["SVC={{.Service.Name}}", "SLOT={{.Task.Slot}}",
                 "NODE={{.Node.Hostname}}"],
            hostname="{{.Service.Name}}-{{.Task.Slot}}",
            mounts=[Mount(type="volume",
                          source="data-{{.Task.Slot}}",
                          target="/srv/{{.Service.Name}}",
                          volume_labels={"svc": "{{.Service.Name}}"})])))
    task.service_annotations = Annotations(name="web", labels={"env": "prod"})
    node = ApiNode(id="n1", description=NodeDescription(
        hostname="host1", platform=Platform(os="linux")))
    out = expand_container_spec(task, node)
    assert out.spec.container.env == ["SVC=web", "SLOT=3", "NODE=host1"]
    assert out.spec.container.hostname == "web-3"
    # mounts expand source/target/labels (reference expandMounts)
    m = out.spec.container.mounts[0]
    assert (m.source, m.target) == ("data-3", "/srv/web")
    assert m.volume_labels == {"svc": "web"}
    # the original is untouched
    assert task.spec.container.env[0] == "SVC={{.Service.Name}}"
    assert task.spec.container.mounts[0].source == "data-{{.Task.Slot}}"

    ctx = task_context(task, node)
    assert expand("{{.Service.Labels.env}}", ctx) == "prod"
    with pytest.raises(TemplateError):
        expand("{{.Nope}}", ctx)


@async_test
async def test_swarmd_swarmctl_round_trip():
    """Boot swarmd, drive it with swarmctl commands over the socket."""
    from swarmkit_tpu.cmd import swarmctl as ctl_cmd
    from swarmkit_tpu.cmd import swarmd

    tmp = tempfile.TemporaryDirectory(prefix="swarmd-test-")
    sock = os.path.join(tmp.name, "swarmd.sock")
    args = swarmd.build_parser().parse_args([
        "--state-dir", os.path.join(tmp.name, "state"),
        "--listen-control-api", sock,
        "--node-id", "m1", "--manager",
        "--election-tick", "4", "--backend", "inproc",
        "--executor", "test",
    ])
    # fast ticks for tests
    node = await swarmd.run(args)
    node.config.tick_interval = 0.05
    try:
        for _ in range(200):
            if node.is_leader():
                break
            await asyncio.sleep(0.05)
        assert node.is_leader()

        async def ctl(*argv):
            out = io.StringIO()
            rc = await ctl_cmd.run(
                ctl_cmd.build_parser().parse_args(
                    ["--socket", sock, *argv]), out=out)
            return rc, out.getvalue()

        rc, out = await ctl("cluster-inspect")
        assert rc == 0 and "default" in out

        rc, out = await ctl("node-ls")
        assert rc == 0 and "m1" in out and "manager" in out

        rc, out = await ctl("service-create", "--name", "web",
                            "--image", "nginx", "--replicas", "2",
                            "--label", "tier=frontend",
                            "--hostname", "web-{{.Task.Slot}}",
                            "--command", "serve", "--arg=--port=80",
                            "--restart-window", "120",
                            "--generic-resource", "cpu-chip=0",
                            "--limit-cpu", "2", "--limit-memory", "1024",
                            "--log-driver", "json-file",
                            "--log-opt", "max-size=10m")
        assert rc == 0
        svc_id = json.loads(out)["id"]
        rc, out = await ctl("service-inspect", "web")
        spec = json.loads(out)["spec"]
        assert spec["annotations"]["labels"] == {"tier": "frontend"}
        cont = spec["task"]["container"]
        assert cont["hostname"] == "web-{{.Task.Slot}}"
        assert cont["command"] == ["serve"]
        assert cont["args"] == ["--port=80"]
        assert spec["task"]["restart"]["window"] == 120
        assert spec["task"]["resources"]["limits"]["nano_cpus"] == 2_000_000_000
        assert spec["task"]["log_driver"] == {
            "name": "json-file", "options": {"max-size": "10m"}}

        rc, out = await ctl("service-ls")
        assert "web" in out

        # tasks appear and run (the daemon's own agent executes them)
        for _ in range(200):
            rc, out = await ctl("task-ls", "--service", svc_id)
            lines = [l for l in out.splitlines() if "RUNNING" in l]
            if len(lines) == 2:
                break
            await asyncio.sleep(0.05)
        assert len(lines) == 2, out

        # positional refs resolve by NAME (reference cmd/swarmctl
        # service/util.go getService) — scale and poll via "web"
        rc, out = await ctl("service-scale", "web", "4")
        assert rc == 0
        for _ in range(200):
            rc, out = await ctl("task-ls", "--service", "web")
            if len([l for l in out.splitlines() if "RUNNING" in l]) == 4:
                break
            await asyncio.sleep(0.05)

        # ...and by unique id prefix
        rc, out = await ctl("service-inspect", svc_id[:8])
        assert rc == 0 and json.loads(out)["id"] == svc_id

        rc, out = await ctl("secret-create", "db-pass", "--data", "hunter2")
        assert rc == 0
        rc, out = await ctl("secret-ls")
        assert "db-pass" in out

        rc, out = await ctl("network-create", "--name", "overlay1")
        assert rc == 0
        rc, out = await ctl("service-rm", svc_id)
        assert rc == 0
        rc, out = await ctl("service-ls")
        assert "web" not in out

        # error surface: inspect a missing service
        rc, out = await ctl("service-inspect", "nope")
        assert rc == 1
    finally:
        await node._ctl_server.stop()
        await node.stop()


def test_parse_mount():
    from swarmkit_tpu.cmd.swarmctl import CtlError, _parse_mount

    assert _parse_mount("type=bind,source=/x,target=/y,readonly") == {
        "type": "bind", "source": "/x", "target": "/y", "read_only": True}
    assert _parse_mount("target=/y")["type"] == "bind"   # default
    with pytest.raises(CtlError):
        _parse_mount("type=bind,bogus=1,target=/y")


def test_service_spec_generic_resource_errors_are_ctl_errors():
    """Bad --generic-resource values surface as CtlError (clean CLI
    message), never a raw traceback; negatives are rejected client-side."""
    from swarmkit_tpu.cmd.swarmctl import CtlError, _service_spec, build_parser

    def parse(*extra):
        return build_parser().parse_args([
            "service-create", "--name", "x", "--image", "img", *extra])

    with pytest.raises(CtlError):
        _service_spec(parse("--generic-resource", "tpu-chip=two"))
    with pytest.raises(CtlError):
        _service_spec(parse("--generic-resource", "tpu-chip=-4"))
    spec = _service_spec(parse("--generic-resource", "tpu-chip=2"))
    assert spec["task"]["resources"]["reservations"]["generic"] == {
        "tpu-chip": 2}


@async_test
async def test_resolve_ref_names_prefixes_ambiguity():
    """_resolve_ref: exact id > name > unique id prefix; ambiguity and
    absence are CtlErrors (reference cmd/swarmctl/*/util.go)."""
    from swarmkit_tpu.cmd.swarmctl import CtlError, _resolve_ref

    class FakeClient:
        def __init__(self, objs):
            self.objs = objs

        async def call(self, method, **kw):
            if method.endswith(".inspect"):
                for o in self.objs:
                    if o["id"] == kw.get("id"):
                        return o
                raise CtlError(f"{kw.get('id')} not found", "not_found")
            return self.objs

    svc = lambda i, nm: {"id": i,
                         "spec": {"annotations": {"name": nm}}}
    c = FakeClient([svc("abc123", "web"), svc("abd456", "api"),
                    svc("zz9", "abc123x")])
    assert await _resolve_ref(c, "service", "abc123") == "abc123"   # id
    assert await _resolve_ref(c, "service", "web") == "abc123"      # name
    assert await _resolve_ref(c, "service", "api") == "abd456"      # name
    assert await _resolve_ref(c, "service", "abd") == "abd456"      # prefix
    with pytest.raises(CtlError, match="ambiguous"):
        await _resolve_ref(c, "service", "ab")      # abc123 + abd456
    with pytest.raises(CtlError, match="not found"):
        await _resolve_ref(c, "service", "nope")

    # nodes resolve by description.hostname
    nodes = FakeClient([
        {"id": "n1", "description": {"hostname": "worker-a"}},
        {"id": "n2", "description": {"hostname": "worker-b"}},
        {"id": "n3", "description": {"hostname": "worker-b"}},
    ])
    assert await _resolve_ref(nodes, "node", "worker-a") == "n1"
    with pytest.raises(CtlError, match="ambiguous"):
        await _resolve_ref(nodes, "node", "worker-b")


@async_test
async def test_swarmd_autolock_bootstrap():
    """`swarmd --autolock` enables manager autolock at bootstrap and
    mints the unlock key (reference swarmd --autolock flag)."""
    from swarmkit_tpu.cmd import swarmctl as ctl_cmd
    from swarmkit_tpu.cmd import swarmd

    tmp = tempfile.TemporaryDirectory(prefix="swarmd-autolock-")
    sock = os.path.join(tmp.name, "swarmd.sock")
    args = swarmd.build_parser().parse_args([
        "--state-dir", os.path.join(tmp.name, "state"),
        "--listen-control-api", sock,
        "--node-id", "m1", "--manager", "--autolock",
        "--election-tick", "4", "--backend", "inproc",
        "--executor", "test",
    ])
    node = await swarmd.run(args)
    try:
        async def ctl(*argv):
            out = io.StringIO()
            rc = await ctl_cmd.run(
                ctl_cmd.build_parser().parse_args(
                    ["--socket", sock, *argv]), out=out)
            return rc, out.getvalue()

        for _ in range(300):
            rc, out = await ctl("cluster-unlock-key")
            if rc == 0 and json.loads(out).get("autolock"):
                break
            await asyncio.sleep(0.05)
        data = json.loads(out)
        assert data["autolock"] is True
        assert data["unlock_key"].startswith("SWMKEY-")
    finally:
        await node._ctl_server.stop()
        await node.stop()


@async_test
async def test_rafttool_dump():
    """Write real raft state via a manager, then dump it offline."""
    import io as _io

    from swarmkit_tpu.cmd.rafttool import dump_snapshot, dump_wal
    from swarmkit_tpu.manager.manager import Manager
    from swarmkit_tpu.raft.transport import Network
    from swarmkit_tpu.api import (
        ContainerSpec as CS, ReplicatedService, ServiceSpec, TaskSpec as TS,
    )

    tmp = tempfile.TemporaryDirectory(prefix="rafttool-test-")
    state = os.path.join(tmp.name, "m1")
    m = Manager(node_id="m1", addr="m1:4242", network=Network(seed=2),
                state_dir=state, tick_interval=0.05, election_tick=4)
    await m.start()
    for _ in range(100):
        if m.is_leader():
            break
        await asyncio.sleep(0.05)
    await m.control_api.create_service(ServiceSpec(
        annotations=Annotations(name="web"),
        task=TS(container=CS(image="nginx")),
        replicated=ReplicatedService(replicas=1)))
    await m.stop()

    out = _io.StringIO()
    rc = dump_wal(state, out=out)
    assert rc == 0
    dump = out.getvalue()
    assert "NORMAL" in dump
    assert "web" in dump  # the create-service request decoded


@async_test
async def test_template_expansion_through_agent():
    """A templated env var reaches the executor expanded (reference:
    dockerapi controller + template.ExpandContainerSpec)."""
    import random

    from swarmkit_tpu.agent import Agent, AgentConfig
    from swarmkit_tpu.agent.testutils import TestExecutor
    from swarmkit_tpu.api import (
        Node, NodeSpec, NodeState, Task as ApiTask, TaskStatus,
    )
    from swarmkit_tpu.api.objects import NodeStatus
    from swarmkit_tpu.manager.dispatcher import Dispatcher
    from swarmkit_tpu.store.memory import MemoryStore

    store = MemoryStore()
    d = Dispatcher(store, rng=random.Random(0))
    await store.update(lambda tx: tx.create(Node(
        id="n1", spec=NodeSpec(annotations=Annotations(name="n1")),
        description=NodeDescription(hostname="realhost"),
        status=NodeStatus(state=NodeState.UNKNOWN))))
    await d.start(mark_unknown=False)
    ex = TestExecutor(hostname="realhost")
    agent = Agent(AgentConfig(node_id="n1", executor=ex,
                              connect=lambda: d))
    await agent.start()
    await agent.ready()

    t = ApiTask(id="t1", node_id="n1", service_id="s1",
                spec=TaskSpec(container=ContainerSpec(
                    image="img", env=["WHERE={{.Node.Hostname}}"])),
                status=TaskStatus(state=TaskState.ASSIGNED),
                desired_state=int(TaskState.RUNNING))
    t.service_annotations = Annotations(name="websvc")
    await store.update(lambda tx: tx.create(t))
    for _ in range(400):
        if "t1" in ex.controllers:
            break
        await asyncio.sleep(0.005)
    assert "t1" in ex.controllers
    assert ex.controllers["t1"].task.spec.container.env == ["WHERE=realhost"]
    await agent.stop()
    await d.stop()


@async_test
async def test_swarmctl_metrics_shows_latency_percentiles():
    """`swarmctl metrics` surfaces hot-path latency percentiles
    (reference names from raft.go:69-71 / memory.go:81-110)."""
    from swarmkit_tpu.cmd import swarmctl as ctl_cmd
    from swarmkit_tpu.cmd import swarmd

    tmp = tempfile.TemporaryDirectory(prefix="swarmd-metrics-")
    sock = os.path.join(tmp.name, "swarmd.sock")
    args = swarmd.build_parser().parse_args([
        "--state-dir", os.path.join(tmp.name, "state"),
        "--listen-control-api", sock,
        "--node-id", "m1", "--manager",
        "--election-tick", "4", "--backend", "inproc",
        "--executor", "test",
    ])
    node = await swarmd.run(args)
    try:
        for _ in range(200):
            if node.is_leader():
                break
            await asyncio.sleep(0.05)
        out = io.StringIO()
        rc = await ctl_cmd.run(
            ctl_cmd.build_parser().parse_args(
                ["--socket", sock, "metrics"]), out=out)
        assert rc == 0
        data = json.loads(out.getvalue())
        timers = data["timers"]
        import swarmkit_tpu.utils.metrics as m
        assert m.RAFT_PROPOSE_LATENCY in timers
        assert "p99" in timers[m.RAFT_PROPOSE_LATENCY]
        assert "swarm_manager_leader" in data["gauges"]
        assert data["gauges"]["swarm_manager_leader"] == 1.0
    finally:
        await node.stop()
        tmp.cleanup()


@async_test
async def test_swarmctl_service_logs():
    """`swarmctl service-logs` tails task output over the control socket
    (reference: the swarm-level `docker service logs` workflow)."""
    from swarmkit_tpu.cmd import swarmctl as ctl_cmd
    from swarmkit_tpu.cmd import swarmd

    tmp = tempfile.TemporaryDirectory(prefix="swarmd-logs-")
    sock = os.path.join(tmp.name, "swarmd.sock")
    args = swarmd.build_parser().parse_args([
        "--state-dir", os.path.join(tmp.name, "state"),
        "--listen-control-api", sock,
        "--node-id", "m1", "--manager",
        "--election-tick", "4", "--backend", "inproc",
        "--executor", "test",
    ])
    node = await swarmd.run(args)
    try:
        for _ in range(200):
            if node.is_leader():
                break
            await asyncio.sleep(0.05)

        async def ctl(*argv):
            out = io.StringIO()
            rc = await ctl_cmd.run(
                ctl_cmd.build_parser().parse_args(
                    ["--socket", sock, *argv]), out=out)
            return rc, out.getvalue()

        rc, out = await ctl("service-create", "--name", "logged",
                            "--image", "img", "--replicas", "1")
        assert rc == 0
        svc_id = json.loads(out)["id"]
        for _ in range(200):
            rc, out = await ctl("task-ls", "--service", svc_id)
            if "RUNNING" in out:
                break
            await asyncio.sleep(0.05)

        # the TestController wrote "started"; add an app line
        ex = node.config.executor
        ctl_obj = next(c for c in ex.controllers.values()
                       if c.task.service_id == svc_id)
        ctl_obj.write_log("hello from the task")

        # non-follow returns the backlog and exits
        rc, out = await ctl("service-logs", svc_id, "--tail", "5")
        assert rc == 0, out
        assert "started" in out and "hello from the task" in out
        assert "OUT |" in out

        # task-id selector works too
        rc, out = await ctl("service-logs", ctl_obj.task.id, "--task")
        assert rc == 0 and "hello from the task" in out
    finally:
        await node._ctl_server.stop()
        await node.stop()


@async_test
async def test_swarmctl_service_update_and_rollback():
    """`swarmctl service-update` drives the update supervisor (update
    config flags incl. start-first order) and `service-rollback` restores
    the previous spec (reference: cmd/swarmctl/service update flags;
    rollback path updater.go:587)."""
    from swarmkit_tpu.cmd import swarmctl as ctl_cmd
    from swarmkit_tpu.cmd import swarmd

    tmp = tempfile.TemporaryDirectory(prefix="swarmd-upd-")
    sock = os.path.join(tmp.name, "swarmd.sock")
    args = swarmd.build_parser().parse_args([
        "--state-dir", os.path.join(tmp.name, "state"),
        "--listen-control-api", sock,
        "--node-id", "m1", "--manager",
        "--election-tick", "4", "--backend", "inproc",
        "--executor", "test",
    ])
    node = await swarmd.run(args)
    try:
        for _ in range(200):
            if node.is_leader():
                break
            await asyncio.sleep(0.05)

        async def ctl(*argv):
            out = io.StringIO()
            rc = await ctl_cmd.run(
                ctl_cmd.build_parser().parse_args(
                    ["--socket", sock, *argv]), out=out)
            return rc, out.getvalue()

        async def wait_running(svc_id, want, image=None, timeout=15.0):
            store = node.manager.store
            from swarmkit_tpu.store.by import ByService
            deadline = asyncio.get_running_loop().time() + timeout
            while asyncio.get_running_loop().time() < deadline:
                ts = [t for t in store.find("task", ByService(svc_id))
                      if t.status.state == TaskState.RUNNING
                      and int(t.desired_state) == int(TaskState.RUNNING)]
                if image is not None:
                    ts = [t for t in ts
                          if t.spec.container.image == image]
                if len(ts) == want:
                    return ts
                await asyncio.sleep(0.05)
            raise AssertionError(
                f"never saw {want} running {image or ''} tasks")

        rc, out = await ctl("service-create", "--name", "web",
                            "--image", "img1", "--replicas", "3")
        assert rc == 0
        svc_id = json.loads(out)["id"]
        await wait_running(svc_id, 3, "img1")

        # rolling update to img2 with explicit update-config flags
        rc, out = await ctl(
            "service-update", svc_id, "--image", "img2",
            "--update-parallelism", "1", "--update-order", "start-first",
            "--update-failure-action", "continue",
            "--update-monitor", "0.2", "--update-delay", "0")
        assert rc == 0, out
        updated = json.loads(out)
        assert updated["spec"]["task"]["container"]["image"] == "img2"
        assert updated["spec"]["update"]["order"] == 1      # start-first
        assert updated["spec"]["update"]["parallelism"] == 1
        await wait_running(svc_id, 3, "img2")

        # update status reaches completed
        deadline = asyncio.get_running_loop().time() + 10
        while asyncio.get_running_loop().time() < deadline:
            rc, out = await ctl("service-inspect", svc_id)
            st = json.loads(out).get("update_status") or {}
            if st.get("state") == "completed":
                break
            await asyncio.sleep(0.05)
        assert st.get("state") == "completed", st

        # manual rollback restores img1
        rc, out = await ctl("service-rollback", svc_id)
        assert rc == 0, out
        assert json.loads(out)["spec"]["task"]["container"]["image"] == "img1"
        await wait_running(svc_id, 3, "img1")

        # a second rollback has nothing to restore (error -> stderr, rc 1)
        rc, out = await ctl("service-rollback", svc_id)
        assert rc == 1

        # container/label/restart flags merge into the live spec, leaving
        # unrelated fields (the image) untouched
        rc, out = await ctl(
            "service-update", svc_id, "--label-add", "team=infra",
            "--command", "run", "--restart-window", "30",
            "--hostname", "web-{{.Task.Slot}}")
        assert rc == 0, out
        upd2 = json.loads(out)["spec"]
        assert upd2["annotations"]["labels"] == {"team": "infra"}
        assert upd2["task"]["container"]["command"] == ["run"]
        assert upd2["task"]["container"]["hostname"] == "web-{{.Task.Slot}}"
        assert upd2["task"]["restart"]["window"] == 30
        assert upd2["task"]["container"]["image"] == "img1"  # untouched
        rc, out = await ctl("service-update", svc_id, "--label-rm", "team")
        assert json.loads(out)["spec"]["annotations"]["labels"] == {}
    finally:
        await node._ctl_server.stop()
        await node.stop()


@requires_cryptography  # worker admission flows through CA cert issuance
@async_test
async def test_swarmctl_node_update_availability_and_labels():
    """`swarmctl node-update --availability drain` evicts the node's tasks
    (constraint enforcer) and the scheduler re-places them elsewhere;
    `--availability active` readmits it; `--label-add/--label-rm` edit the
    spec labels the constraint language reads (reference:
    cmd/swarmctl/node/update.go drain/activate + label flags)."""
    from swarmkit_tpu.cmd import swarmctl as ctl_cmd
    from swarmkit_tpu.cmd import swarmd
    from tests.test_grpc_transport import free_port

    tmp = tempfile.TemporaryDirectory(prefix="swarmd-drain-")
    sock = os.path.join(tmp.name, "m1.sock")
    m_addr = f"127.0.0.1:{free_port()}"
    m_args = swarmd.build_parser().parse_args([
        "--state-dir", os.path.join(tmp.name, "m1"),
        "--listen-control-api", sock,
        "--listen-remote-api", m_addr,
        "--node-id", "m1", "--manager", "--election-tick", "4",
        "--executor", "test",
    ])
    manager_node = await swarmd.run(m_args)
    worker_node = None
    try:
        for _ in range(200):
            if manager_node.is_leader():
                break
            await asyncio.sleep(0.05)
        lead = manager_node._running_manager()
        for _ in range(200):
            if lead.store.find("cluster"):
                break
            await asyncio.sleep(0.05)
        token = lead.store.find("cluster")[0].root_ca.join_token_worker

        w_args = swarmd.build_parser().parse_args([
            "--state-dir", os.path.join(tmp.name, "w1"),
            "--listen-control-api", os.path.join(tmp.name, "w1.sock"),
            "--listen-remote-api", f"127.0.0.1:{free_port()}",
            "--node-id", "w1",
            "--join-addr", m_addr, "--join-token", token,
            "--executor", "test",
        ])
        worker_node = await swarmd.run(w_args)

        from swarmkit_tpu.api import NodeState
        for _ in range(400):
            n = lead.store.get("node", "w1")
            if n is not None and n.status.state == NodeState.READY:
                break
            await asyncio.sleep(0.05)
        assert lead.store.get("node", "w1").status.state == NodeState.READY

        async def ctl(*argv):
            out = io.StringIO()
            rc = await ctl_cmd.run(
                ctl_cmd.build_parser().parse_args(
                    ["--socket", sock, *argv]), out=out)
            return rc, out.getvalue()

        rc, out = await ctl("service-create", "--name", "web",
                            "--image", "img", "--replicas", "4")
        assert rc == 0, out
        svc_id = json.loads(out)["id"]

        from swarmkit_tpu.store.by import ByService

        def running_by_node():
            by: dict[str, int] = {}
            for t in lead.store.find("task", ByService(svc_id)):
                if t.status.state == TaskState.RUNNING \
                        and int(t.desired_state) == int(TaskState.RUNNING):
                    by[t.node_id] = by.get(t.node_id, 0) + 1
            return by

        # tasks spread across both nodes first
        for _ in range(400):
            by = running_by_node()
            if sum(by.values()) == 4 and by.get("w1", 0) > 0:
                break
            await asyncio.sleep(0.05)
        assert by.get("w1", 0) > 0, by

        # DRAIN w1 through the CLI: enforcer evicts, scheduler re-places
        rc, out = await ctl("node-update", "w1", "--availability", "drain")
        assert rc == 0, out
        assert json.loads(out)["spec"]["availability"] == 2  # DRAIN
        for _ in range(400):
            by = running_by_node()
            if by.get("w1", 0) == 0 and by.get("m1", 0) == 4:
                break
            await asyncio.sleep(0.05)
        assert by == {"m1": 4}, by

        # reactivate + labels; scale up so w1 gets work again
        rc, out = await ctl("node-update", "w1",
                            "--availability", "active",
                            "--label-add", "zone=east",
                            "--label-add", "tier=gpu")
        assert rc == 0, out
        spec = json.loads(out)["spec"]
        assert spec["availability"] == 0
        assert spec["annotations"]["labels"] == {"zone": "east",
                                                 "tier": "gpu"}
        rc, out = await ctl("node-update", "w1", "--label-rm", "tier")
        assert rc == 0, out
        assert json.loads(out)["spec"]["annotations"]["labels"] == \
            {"zone": "east"}

        rc, out = await ctl("service-scale", svc_id, "8")
        assert rc == 0, out
        for _ in range(400):
            by = running_by_node()
            if sum(by.values()) == 8 and by.get("w1", 0) > 0:
                break
            await asyncio.sleep(0.05)
        assert by.get("w1", 0) > 0, f"reactivated node got no work: {by}"
    finally:
        if worker_node is not None:
            await worker_node.stop()
        await manager_node._ctl_server.stop()
        await manager_node.stop()


@async_test
async def test_swarmd_listen_debug_diagnoses_wedged_store():
    """`swarmd --listen-debug` serves the live diagnostic surface: asyncio
    task dump, store wedge state, watch-queue depths, metrics registry —
    and a wedged store is readable THROUGH the endpoint (reference:
    swarmd --listen-debug pprof/expvar, cmd/swarmd/main.go:4-8,183)."""
    from swarmkit_tpu.cmd import swarmd

    tmp = tempfile.TemporaryDirectory(prefix="swarmd-debug-")
    sock = os.path.join(tmp.name, "swarmd.sock")
    dbg_sock = os.path.join(tmp.name, "debug.sock")
    args = swarmd.build_parser().parse_args([
        "--state-dir", os.path.join(tmp.name, "state"),
        "--listen-control-api", sock,
        "--listen-debug", dbg_sock,
        "--node-id", "m1", "--manager",
        "--election-tick", "4", "--backend", "inproc",
        "--executor", "test",
    ])
    node = await swarmd.run(args)
    try:
        for _ in range(200):
            if node.is_leader():
                break
            await asyncio.sleep(0.05)
        assert node.is_leader()

        async def get(path):
            r, w = await asyncio.open_unix_connection(dbg_sock)
            w.write(f"GET {path} HTTP/1.0\r\n\r\n".encode())
            await w.drain()
            raw = await r.read()
            w.close()
            head, _, body = raw.partition(b"\r\n\r\n")
            status = int(head.split()[1])
            return status, json.loads(body)

        status, tasks = await get("/debug/tasks")
        assert status == 200
        assert len(tasks["tasks"]) > 3          # raft loop, dispatcher, ...
        assert any("run" in t["coro"] for t in tasks["tasks"])

        status, store_state = await get("/debug/store")
        assert status == 200
        assert store_state["wedged"] is False
        assert "node" in store_state["objects"]

        status, queues = await get("/debug/queues")
        assert status == 200
        assert queues["watchers"] > 0           # control loops watching

        status, metrics = await get("/debug/metrics")
        assert status == 200

        # WEDGE the store: a proposal that never commits (simulated via
        # the same in-flight bookkeeping wedged() watches) must be
        # diagnosable through the endpoint while the daemon is stuck
        store = node._running_manager().store
        store._in_flight[999999] = store._now() - store.WEDGE_TIMEOUT - 1
        try:
            status, store_state = await get("/debug/store")
            assert status == 200
            assert store_state["wedged"] is True
            assert store_state["in_flight_proposals"] >= 1
            assert max(store_state["in_flight_ages_s"]) \
                > store.WEDGE_TIMEOUT
            status, allvars = await get("/debug/vars")
            assert allvars["store"]["wedged"] is True
            assert allvars["is_leader"] is True
        finally:
            store._in_flight.pop(999999, None)

        status, err = await get("/debug/nope")
        assert status == 404
    finally:
        await node._debug_server.stop()
        await node._ctl_server.stop()
        await node.stop()


@async_test
async def test_swarmctl_global_mode_networks_secrets_and_task_inspect():
    """Round-trip the round-5 CLI additions: network-create --driver
    --subnet, service-create --mode global / --network / --secret,
    task-inspect (reference: cmd/swarmctl service flags + task inspect)."""
    from swarmkit_tpu.cmd import swarmctl as ctl_cmd
    from swarmkit_tpu.cmd import swarmd

    tmp = tempfile.TemporaryDirectory(prefix="swarmd-cli5-")
    sock = os.path.join(tmp.name, "swarmd.sock")
    args = swarmd.build_parser().parse_args([
        "--state-dir", os.path.join(tmp.name, "state"),
        "--listen-control-api", sock,
        "--node-id", "m1", "--manager",
        "--election-tick", "4", "--backend", "inproc",
        "--executor", "test",
    ])
    node = await swarmd.run(args)
    try:
        for _ in range(200):
            if node.is_leader():
                break
            await asyncio.sleep(0.05)

        async def ctl(*argv):
            out = io.StringIO()
            rc = await ctl_cmd.run(
                ctl_cmd.build_parser().parse_args(
                    ["--socket", sock, *argv]), out=out)
            return rc, out.getvalue()

        rc, out = await ctl("network-create", "--name", "front",
                            "--subnet", "10.42.0.0/24")
        assert rc == 0, out
        net = json.loads(out)
        rc, out = await ctl("secret-create", "apikey", "--data", "k3y")
        assert rc == 0, out

        # unknown network/secret names fail cleanly
        rc, out = await ctl("service-create", "--name", "bad",
                            "--image", "img", "--network", "nope")
        assert rc == 1

        rc, out = await ctl(
            "service-create", "--name", "g1", "--image", "img",
            "--mode", "global", "--network", "front",
            "--secret", "apikey")
        assert rc == 0, out
        svc = json.loads(out)
        assert svc["spec"]["mode"] == 1 and "global_" in svc["spec"]
        assert svc["spec"]["task"]["networks"] == [net["id"]]
        refs = svc["spec"]["task"]["container"]["secrets"]
        assert refs and refs[0]["secret_name"] == "apikey"

        # global mode: one task per node, with the network allocated
        for _ in range(300):
            rc, out = await ctl("task-ls", "--service", svc["id"])
            lines = [l for l in out.splitlines() if "RUNNING" in l]
            if len(lines) == 1:
                break
            await asyncio.sleep(0.05)
        assert len(lines) == 1, out
        task_id = lines[0].split("\t")[0]
        rc, out = await ctl("task-inspect", task_id)
        assert rc == 0, out
        t = json.loads(out)
        assert t["networks"] and t["networks"][0]["network_id"] == net["id"]
        addr = t["networks"][0]["addresses"][0]
        assert addr.startswith("10.42.0."), addr
    finally:
        await node._ctl_server.stop()
        await node.stop()


def test_service_spec_builder_resources_and_restart():
    """service-create --reserve-cpu/--reserve-memory/--restart-* flags map
    onto TaskSpec.resources.reservations and TaskSpec.restart (reference:
    cmd/swarmctl/service/flagparser flags.go/restart.go)."""
    from swarmkit_tpu.cmd import swarmctl as ctl_cmd

    args = ctl_cmd.build_parser().parse_args([
        "service-create", "--name", "r", "--image", "img",
        "--reserve-cpu", "0.5", "--reserve-memory", "1048576",
        "--restart-condition", "failure", "--restart-delay", "2.5",
        "--restart-max-attempts", "3"])
    spec = ctl_cmd._service_spec(args)
    res = spec["task"]["resources"]["reservations"]
    assert res["nano_cpus"] == 500_000_000
    assert res["memory_bytes"] == 1048576
    r = spec["task"]["restart"]
    assert r == {"condition": 1, "delay": 2.5, "max_attempts": 3}
    # spec round-trips through the typed model
    from swarmkit_tpu.api import ServiceSpec
    from swarmkit_tpu.api.specs import RestartCondition
    typed = ServiceSpec.from_dict(spec)
    assert typed.task.resources.reservations.nano_cpus == 500_000_000
    assert typed.task.restart.condition == RestartCondition.ON_FAILURE
    assert typed.task.restart.max_attempts == 3


@async_test
async def test_swarmctl_cluster_update_settings_flow_to_components():
    """cluster-update edits the replicated ClusterSpec; components re-read
    it on EventUpdateCluster (reference: cmd/swarmctl/cluster/update.go;
    dynamic config per SURVEY §5)."""
    from swarmkit_tpu.cmd import swarmctl as ctl_cmd
    from swarmkit_tpu.cmd import swarmd

    tmp = tempfile.TemporaryDirectory(prefix="swarmd-clup-")
    sock = os.path.join(tmp.name, "swarmd.sock")
    args = swarmd.build_parser().parse_args([
        "--state-dir", os.path.join(tmp.name, "state"),
        "--listen-control-api", sock,
        "--node-id", "m1", "--manager",
        "--election-tick", "4", "--backend", "inproc",
        "--executor", "test",
    ])
    node = await swarmd.run(args)
    try:
        for _ in range(200):
            if node.is_leader():
                break
            await asyncio.sleep(0.05)

        async def ctl(*argv):
            out = io.StringIO()
            rc = await ctl_cmd.run(
                ctl_cmd.build_parser().parse_args(
                    ["--socket", sock, *argv]), out=out)
            return rc, out.getvalue()

        rc, out = await ctl("cluster-update", "--task-history", "9",
                            "--heartbeat-period", "2.5",
                            "--cert-expiry", "3600")
        assert rc == 0, out
        cl = json.loads(out)
        assert cl["spec"]["orchestration"][
            "task_history_retention_limit"] == 9
        assert cl["spec"]["dispatcher"]["heartbeat_period"] == 2.5
        assert cl["spec"]["ca_config"]["node_cert_expiry"] == 3600

        # the stored object reflects it (components watch this object)
        lead = node._running_manager()
        stored = lead.store.find("cluster")[0]
        assert stored.spec.orchestration.task_history_retention_limit == 9
        assert stored.spec.dispatcher.heartbeat_period == 2.5

        # token rotation changes the worker join token; tokens pin the
        # root CA digest, so a no-CA degraded cluster refuses the rotate
        from swarmkit_tpu.ca.certificates import HAVE_CRYPTOGRAPHY
        old = stored.root_ca.join_token_worker
        rc, out = await ctl("cluster-update", "--rotate-worker-token")
        if HAVE_CRYPTOGRAPHY:
            assert rc == 0, out
            new = lead.store.find("cluster")[0].root_ca.join_token_worker
            assert new and new != old
        else:
            assert rc == 1
            assert lead.store.find(
                "cluster")[0].root_ca.join_token_worker == old
    finally:
        await node._ctl_server.stop()
        await node.stop()


@async_test
async def test_swarmctl_inspect_verbs():
    """network/secret/config-inspect round-trip (reference: cmd/swarmctl
    inspect subcommands; secret payloads stay redacted)."""
    from swarmkit_tpu.cmd import swarmctl as ctl_cmd
    from swarmkit_tpu.cmd import swarmd

    tmp = tempfile.TemporaryDirectory(prefix="swarmd-insp-")
    sock = os.path.join(tmp.name, "swarmd.sock")
    args = swarmd.build_parser().parse_args([
        "--state-dir", os.path.join(tmp.name, "state"),
        "--listen-control-api", sock,
        "--node-id", "m1", "--manager",
        "--election-tick", "4", "--backend", "inproc",
        "--executor", "test",
    ])
    node = await swarmd.run(args)
    try:
        for _ in range(200):
            if node.is_leader():
                break
            await asyncio.sleep(0.05)

        async def ctl(*argv):
            out = io.StringIO()
            rc = await ctl_cmd.run(
                ctl_cmd.build_parser().parse_args(
                    ["--socket", sock, *argv]), out=out)
            return rc, out.getvalue()

        rc, out = await ctl("network-create", "--name", "n1",
                            "--subnet", "10.77.0.0/24")
        nid = json.loads(out)["id"]
        rc, out = await ctl("network-inspect", nid)
        assert rc == 0, out
        n = json.loads(out)
        assert n["spec"]["annotations"]["name"] == "n1"

        rc, out = await ctl("secret-create", "s1", "--data", "topsecret")
        sid = json.loads(out)["id"]
        rc, out = await ctl("secret-inspect", sid)
        assert rc == 0, out
        # payload redacted on inspect: neither raw nor base64 form present
        import base64 as _b64
        b64 = _b64.b64encode(b"topsecret").decode()
        assert "topsecret" not in out and b64 not in out
        data = json.loads(out)["spec"].get("data")
        assert not data or data in ({"__b64__": ""}, "")

        rc, out = await ctl("config-create", "c1", "--data", "cfgdata")
        cid = json.loads(out)["id"]
        rc, out = await ctl("config-inspect", cid)
        assert rc == 0, out
        assert json.loads(out)["spec"]["annotations"]["name"] == "c1"
    finally:
        await node._ctl_server.stop()
        await node.stop()


@async_test
async def test_swarmd_generic_node_resources_flag():
    """--generic-node-resources declares operator-defined resources that
    flow into the registered node description and are schedulable
    (reference: cmd/swarmd/main.go:267 + api/genericresource)."""
    from swarmkit_tpu.cmd import swarmd

    tmp = tempfile.TemporaryDirectory(prefix="swarmd-gnr-")
    sock = os.path.join(tmp.name, "swarmd.sock")
    args = swarmd.build_parser().parse_args([
        "--state-dir", os.path.join(tmp.name, "state"),
        "--listen-control-api", sock,
        "--node-id", "m1", "--manager",
        "--election-tick", "4", "--backend", "inproc",
        "--executor", "test",
        "--generic-node-resources", "fpga=2,gpu=UUID1,gpu=UUID2",
    ])
    node = await swarmd.run(args)
    try:
        for _ in range(200):
            if node.is_leader():
                break
            await asyncio.sleep(0.05)
        lead = node._running_manager()
        rec = None
        for _ in range(200):
            rec = lead.store.get("node", "m1")
            if rec is not None and rec.description is not None \
                    and rec.description.resources is not None \
                    and rec.description.resources.generic.get("fpga"):
                break
            await asyncio.sleep(0.05)
        assert rec is not None and rec.description is not None \
            and rec.description.resources is not None, \
            "node never registered with resources"
        res = rec.description.resources
        assert res.generic["fpga"] == 2
        assert res.generic["gpu"] == 2
        assert sorted(res.generic_named["gpu"]) == ["UUID1", "UUID2"]
    finally:
        await node._ctl_server.stop()
        await node.stop()


def test_generic_node_resources_parser_rejects_bad_specs():
    """Mixed discrete/named kinds, duplicate ids, and empty values are
    CLI-parse-time errors (reference: api/genericresource validation)."""
    import pytest as _pytest

    from swarmkit_tpu.cmd.swarmd import (
        _parse_generic_resources, build_parser,
    )

    counts, named = _parse_generic_resources("fpga=2,gpu=U1,gpu=U2")
    assert counts == {"fpga": 2, "gpu": 2}
    assert named == {"gpu": ["U1", "U2"]}

    for bad in ("gpu=2,gpu=UUID1", "gpu=U1,gpu=U1", "fpga", "fpga=",
                "=3", "fpga=0", "fpga=-2", "fp ga=2", "gpu=U 1"):
        with _pytest.raises(ValueError):
            _parse_generic_resources(bad)

    # surrounding whitespace is tolerated (split on ',' leaves it)
    counts, named = _parse_generic_resources(" fpga=2 , gpu=U1 ")
    assert counts == {"fpga": 2, "gpu": 1}

    # argparse surfaces it at parse time, not mid-run — and shows the
    # parser's own message, not argparse's generic "invalid value"
    parser = build_parser()
    with _pytest.raises(SystemExit):
        parser.parse_args(
            ["--manager", "--generic-node-resources", "gpu=2,gpu=U1"])
    import argparse as _argparse
    for action in parser._actions:
        if action.dest == "generic_node_resources":
            with _pytest.raises(_argparse.ArgumentTypeError,
                                match="mixes a discrete count"):
                action.type("gpu=2,gpu=U1")
            break
    else:
        _pytest.fail("--generic-node-resources action not found")

"""Deterministic simulation testing subsystem (swarmkit_tpu/dst/).

Fast tier: invariant checkers against hand-built states (each must trip
exactly its own bit), schedule-generator determinism, FaultPlan lowering,
a small stock explore() (zero violations), and the full mutation pipeline
(detect -> shrink -> artifact -> exact replay) on a pinned seed.

Slow tier: the >=256-schedule x >=100-tick sweep and the field-level
oracle trace live in tests/test_dst_sweep.py.
"""

import dataclasses

import jax.numpy as jnp
import numpy as np
import pytest

from swarmkit_tpu import dst
from swarmkit_tpu.raft.faults import FaultPlan, plan_to_schedule
from swarmkit_tpu.raft.sim import run_schedule
from swarmkit_tpu.raft.sim.state import (
    CANDIDATE, FOLLOWER, LEADER, SimConfig, init_state,
)

CFG3 = SimConfig(n=3, log_len=64, window=8, apply_batch=16, max_props=8,
                 keep=4, election_tick=10, seed=7)
CFG5 = SimConfig(n=5, log_len=64, window=8, apply_batch=16, max_props=8,
                 keep=4, election_tick=10, seed=0)


def _bits(state, cfg=CFG5) -> int:
    return int(dst.check_state(state, cfg))


def _arr(base, **updates):
    """dataclasses.replace with each update applied via .at[idx].set."""
    fields = {}
    for name, pairs in updates.items():
        a = getattr(base, name)
        for idx, val in pairs:
            a = a.at[idx].set(val)
        fields[name] = a
    return dataclasses.replace(base, **fields)


# ---------------------------------------------------------------------------
# invariant checkers: each hand-built state trips exactly the right bit


def test_clean_init_state_has_no_violations():
    st = init_state(CFG5)
    assert _bits(st) == 0
    assert int(dst.check_transition(st, st)) == 0


def test_election_safety_two_leaders_same_term():
    st = _arr(init_state(CFG5),
              role=[(0, LEADER), (1, LEADER)],
              term=[(0, 5), (1, 5)])
    assert _bits(st) == dst.ELECTION_SAFETY


def test_election_safety_allows_stale_minority_leader():
    # two leaders at DIFFERENT terms is the legal partition aftermath
    st = _arr(init_state(CFG5),
              role=[(0, LEADER), (1, LEADER)],
              term=[(0, 5), (1, 4)])
    assert _bits(st) == 0


def test_log_matching_same_index_term_different_payload():
    # index 1 lives in slot 0; rows 0 and 1 agree on its term but not data
    st = _arr(init_state(CFG5),
              last=[(0, 1), (1, 1)],
              log_term=[((0, 0), 1), ((1, 0), 1)],
              log_data=[((0, 0), 10), ((1, 0), 11)])
    assert _bits(st) == dst.LOG_MATCHING
    same = _arr(st, log_data=[((1, 0), 10)])
    assert _bits(same) == 0


def test_log_matching_ignores_same_index_different_term():
    # conflicting-term entries are exactly what raft overwrites — legal
    st = _arr(init_state(CFG5),
              last=[(0, 1), (1, 1)],
              log_term=[((0, 0), 1), ((1, 0), 2)],
              log_data=[((0, 0), 10), ((1, 0), 11)])
    assert _bits(st) == 0


def test_leader_completeness_top_term_leader_missing_commits():
    st = _arr(init_state(CFG5),
              role=[(0, LEADER)],
              term=[(0, 5)],
              last=[(1, 3)],
              commit=[(1, 3)],
              log_term=[((1, 0), 1), ((1, 1), 1), ((1, 2), 1)])
    assert _bits(st) == dst.LEADER_COMPLETENESS


def test_leader_completeness_exempts_stale_leader():
    # same shape, but the lagging leader is NOT at the global max term
    st = _arr(init_state(CFG5),
              role=[(0, LEADER)],
              term=[(0, 3), (1, 5)],
              last=[(1, 3)],
              commit=[(1, 3)],
              log_term=[((1, 0), 1), ((1, 1), 1), ((1, 2), 1)])
    assert _bits(st) == 0


def test_commit_monotonic_regression_and_apply_overrun():
    prev = _arr(init_state(CFG5), commit=[(0, 3)], last=[(0, 3)])
    lost = _arr(init_state(CFG5), commit=[(0, 2)], last=[(0, 3)])
    assert int(dst.check_transition(prev, lost)) == dst.COMMIT_MONOTONIC
    ahead = _arr(init_state(CFG5), applied=[(0, 1)])
    assert int(dst.check_transition(init_state(CFG5), ahead)) \
        == dst.COMMIT_MONOTONIC


def test_checksum_agreement_same_applied_different_checksum():
    st = _arr(init_state(CFG5),
              last=[(0, 2), (1, 2)],
              commit=[(0, 2), (1, 2)],
              applied=[(0, 2), (1, 2)],
              apply_chk=[(0, 7), (1, 9)])
    assert _bits(st) == dst.CHECKSUM_AGREEMENT
    agree = _arr(st, apply_chk=[(1, 7)])
    assert _bits(agree) == 0


def test_bits_to_names():
    assert dst.bits_to_names(0) == []
    assert dst.bits_to_names(dst.ELECTION_SAFETY | dst.CHECKSUM_AGREEMENT) \
        == ["election_safety", "checksum_agreement"]


# ---------------------------------------------------------------------------
# schedule generation: counter-seeded determinism + the adversary gates


def _leaves(sched):
    return [np.asarray(a) for a in
            (sched.drop, sched.alive, sched.target_leader,
             sched.crash_campaign)]


@pytest.mark.parametrize("profile", dst.PROFILES)
def test_make_schedule_deterministic_per_seed(profile):
    a = dst.make_schedule(CFG3, ticks=24, profile=profile, seed=5, index=3)
    b = dst.make_schedule(CFG3, ticks=24, profile=profile, seed=5, index=3)
    for la, lb in zip(_leaves(a), _leaves(b)):
        assert np.array_equal(la, lb)
    c = dst.make_schedule(CFG3, ticks=24, profile=profile, seed=6, index=3)
    assert any(not np.array_equal(la, lc)
               for la, lc in zip(_leaves(a), _leaves(c)))


def test_make_schedule_rejects_unknown_profile():
    with pytest.raises(KeyError):
        dst.make_schedule(CFG3, ticks=8, profile="nope", seed=0)


def test_make_batch_index_stable_across_widths():
    # schedule (seed, index) must not depend on how wide the sweep runs
    wide, wide_names = dst.make_batch(CFG3, ticks=16, schedules=12, seed=9)
    narrow, narrow_names = dst.make_batch(CFG3, ticks=16, schedules=6, seed=9)
    assert wide_names[:6] == narrow_names
    assert wide_names == [dst.PROFILES[s % len(dst.PROFILES)]
                          for s in range(12)]
    for s in range(6):
        for lw, ln in zip(_leaves(wide.slice(s)), _leaves(narrow.slice(s))):
            assert np.array_equal(lw, ln)


def test_effective_faults_resolves_gates_against_roles():
    role = jnp.asarray([FOLLOWER, CANDIDATE, LEADER])
    alive, drop = dst.schedule.effective_faults(
        role, jnp.zeros((3, 3), bool), jnp.ones((3,), bool),
        jnp.asarray(True), jnp.asarray(True))
    alive, drop = np.asarray(alive), np.asarray(drop)
    assert alive.tolist() == [True, False, True]   # candidate crashed
    assert drop[2, :].all() and drop[:, 2].all()   # leader isolated
    assert not drop[0, 1] and not drop[1, 0]       # others untouched


# ---------------------------------------------------------------------------
# FaultPlan -> schedule lowering (raft/faults.py plan_to_schedule)

ROWS3 = {"a": 0, "b": 1, "c": 2}


def test_plan_lowering_down_blocks_edges_into_row():
    arrs = plan_to_schedule(FaultPlan.down("b"), ROWS3, n=3, ticks=10,
                            inject_at=2, heal_at=7)
    assert arrs["drop"][2:7, :, 1].all()
    assert not arrs["drop"][:2].any() and not arrs["drop"][7:].any()
    assert not arrs["drop"][2:7, :, [0, 2]].any()
    assert arrs["alive"].all()


def test_plan_lowering_split_drops_cross_group_edges():
    arrs = plan_to_schedule(FaultPlan.split(("a", "b"), ("c",)), ROWS3,
                            n=3, ticks=4)
    assert arrs["drop"][:, 0, 2].all() and arrs["drop"][:, 2, 0].all()
    assert arrs["drop"][:, 1, 2].all() and arrs["drop"][:, 2, 1].all()
    assert not arrs["drop"][:, 0, 1].any() and not arrs["drop"][:, 1, 0].any()


def test_plan_lowering_delay_gates_edge_open_every_dplus1_ticks():
    # 3-second delay at 1s/tick: edge open only every 4th tick, so traffic
    # lands 3 ticks late on the retry-every-tick synchronous wire
    arrs = plan_to_schedule(FaultPlan.delay("a", "b", 3.0, symmetric=False),
                            ROWS3, n=3, ticks=8)
    assert arrs["drop"][:, 0, 1].tolist() == [True, True, True, False,
                                              True, True, True, False]
    assert not arrs["drop"][:, 1, 0].any()


def test_plan_lowering_crash_and_drop():
    arrs = plan_to_schedule(FaultPlan.crash("c"), ROWS3, n=3, ticks=6,
                            inject_at=1, heal_at=4)
    assert (~arrs["alive"][1:4, 2]).all()
    assert arrs["alive"][:1].all() and arrs["alive"][4:].all()
    arrs = plan_to_schedule(FaultPlan.drop("a", "c", p=1.0), ROWS3,
                            n=3, ticks=5)
    assert arrs["drop"][:, 0, 2].all()


def test_from_fault_plan_wraps_device_schedule():
    sched = dst.from_fault_plan(CFG3, FaultPlan.down("a"), ROWS3, ticks=12,
                                inject_at=3, heal_at=9)
    assert isinstance(sched, dst.FaultSchedule)
    assert sched.ticks == 12
    assert np.asarray(sched.drop)[3:9, :, 0].all()
    assert not np.asarray(sched.target_leader).any()
    assert not np.asarray(sched.crash_campaign).any()


def test_run_schedule_driver_advances_under_clean_schedule():
    drop = jnp.zeros((40, 3, 3), bool)
    alive = jnp.ones((40, 3), bool)
    final, trace = run_schedule(init_state(CFG3), CFG3, drop, alive,
                                prop_count=2)
    assert trace.shape == (40, 3)
    assert int(jnp.max(final.commit)) > 0


# ---------------------------------------------------------------------------
# explore(): stock kernel is invariant-clean; the mutated kernel is caught,
# shrunk, and the repro artifact replays exactly


def test_explore_stock_kernel_clean():
    batch, names = dst.make_batch(CFG3, ticks=30, schedules=6, seed=1)
    res = dst.explore(init_state(CFG3), CFG3, batch, profiles=names)
    assert res.viol.shape == (6,)
    assert res.violating.size == 0, \
        [dst.bits_to_names(int(res.viol[s])) for s in res.violating]
    assert (res.first_tick == -1).all()
    assert res.bits_by_tick.shape == (30, 6)


def test_mutation_caught_shrunk_and_replayable(tmp_path):
    mutation = "commit_no_quorum"
    batch, names = dst.make_batch(CFG5, ticks=100, schedules=24, seed=0)
    res = dst.explore(init_state(CFG5), CFG5, batch, profiles=names,
                      mutation=mutation)
    assert res.violating.size > 0, "mutation escaped the checkers"

    s = int(res.violating[0])
    viol = int(res.viol[s])
    assert viol & dst.LEADER_COMPLETENESS

    # replay of the un-shrunk schedule reproduces explore() exactly
    v0, f0 = dst.replay(CFG5, batch.slice(s), mutation=mutation)
    assert (v0, f0) == (viol, int(res.first_tick[s]))

    small, evals = dst.shrink(CFG5, batch.slice(s), viol, mutation=mutation)
    assert evals > 0
    assert dst.fault_count(small) < dst.fault_count(batch.slice(s))
    v1, f1 = dst.replay(CFG5, small, mutation=mutation)
    assert v1 & viol

    # the same minimal schedule is CLEAN on the stock kernel: the bug is
    # in the mutation, not the adversary
    v2, _ = dst.replay(CFG5, small)
    assert v2 == 0

    # artifact roundtrip: JSON -> schedule -> identical replay
    art = dst.to_artifact(CFG5, small, seed=0, profile=names[s], index=s,
                          prop_count=2, mutation=mutation, viol=v1,
                          first_tick=f1)
    path = tmp_path / "repro.json"
    dst.save_artifact(str(path), art)
    verdict = dst.replay_artifact(dst.load_artifact(str(path)),
                                  with_trace=False)
    assert verdict["matches_recorded"], verdict
    assert verdict["violations"] == dst.bits_to_names(v1)


def test_apply_mutation_rejects_unknown_knob():
    from swarmkit_tpu.dst.explore import apply_mutation

    with pytest.raises(KeyError):
        apply_mutation(init_state(CFG3), CFG3, "made_up")

"""swarm-bench equivalent: leader election + replicated-log throughput for N
simulated managers on one chip (BASELINE.json north star: election + 1M
committed entries @ 4096 managers in < 60 s on v5e-8).

Prints ONE JSON line:
  {"metric": ..., "value": N, "unit": ..., "vs_baseline": N}

vs_baseline is measured committed-entries/sec divided by the north-star rate
(1M entries / 60 s = 16667 entries/s).
"""

from __future__ import annotations

import json
import os
import sys
import time


def log(*a):  # all progress goes to stderr; stdout carries only the JSON line
    print(*a, file=sys.stderr, flush=True)


def main() -> None:
    n = int(os.environ.get("BENCH_N", "4096"))
    target_entries = int(os.environ.get("BENCH_ENTRIES", "1000000"))

    import jax
    import numpy as np

    from swarmkit_tpu.raft.sim import (
        SimConfig, committed_entries, init_state, run_ticks, run_until_leader,
    )

    cfg = SimConfig(n=n, log_len=8192, window=2048, apply_batch=2048,
                    max_props=2048, keep=500, seed=42)
    ticks_needed = (target_entries + cfg.max_props - 1) // cfg.max_props
    log(f"devices: {jax.devices()}  n={n} ticks={ticks_needed}")

    state = init_state(cfg)

    # --- election latency --------------------------------------------------
    t0 = time.perf_counter()
    state, ticks = run_until_leader(state, cfg, max_ticks=500)
    jax.block_until_ready(state.term)
    t_elect = time.perf_counter() - t0
    assert int(ticks) < 500, "no leader elected within 500 ticks — kernel broken"
    log(f"leader elected in {int(ticks)} ticks ({t_elect:.2f}s incl compile)")

    # --- warmup: compile the full-length scan once -------------------------
    t0 = time.perf_counter()
    wu, _ = run_ticks(state, cfg, ticks_needed, prop_count=cfg.max_props)
    jax.block_until_ready(wu.commit)
    log(f"first (compile+run) pass: {time.perf_counter() - t0:.2f}s, "
        f"committed {int(committed_entries(wu))}")

    # --- timed steady-state replication (compiled) -------------------------
    base = int(committed_entries(state))
    t0 = time.perf_counter()
    final, trace = run_ticks(state, cfg, ticks_needed,
                             prop_count=cfg.max_props)
    jax.block_until_ready(final.commit)
    dt = time.perf_counter() - t0

    committed = int(committed_entries(final)) - base
    commit = np.asarray(final.commit)
    applied = np.asarray(final.applied)
    chk = np.asarray(final.apply_chk)
    # safety verification: equal applied => equal state-machine checksum
    by = {}
    for a, c in zip(applied.tolist(), chk.tolist()):
        assert by.setdefault(a, c) == c, f"checksum divergence at applied={a}"
    n_quorum = int((commit >= commit.max() - cfg.max_props).sum())
    assert n_quorum >= n // 2 + 1, f"only {n_quorum} replicas near tip"

    rate = committed / dt
    log(f"committed {committed} entries across {n} managers in {dt:.2f}s "
        f"({rate:,.0f} entries/s); total wall incl election {dt + t_elect:.2f}s")

    baseline_rate = 1_000_000 / 60.0
    print(json.dumps({
        "metric": f"committed-log-entries/sec @ {n} simulated managers "
                  f"(election {int(ticks)} ticks in {t_elect:.2f}s)",
        "value": round(rate, 1),
        "unit": "entries/s",
        "vs_baseline": round(rate / baseline_rate, 3),
    }))


if __name__ == "__main__":
    main()

"""swarm-bench equivalent: leader election + replicated-log throughput for N
simulated managers on one chip (BASELINE.json north star: election + 1M
committed entries @ 4096 managers in < 60 s on v5e-8).

Prints ONE JSON line on stdout, ALWAYS — on failure the line carries an
"error" field with whatever partial results exist and the process exits
nonzero. All progress goes to stderr. Reference harness analogue:
cmd/swarm-bench/benchmark.go:38.

vs_baseline is measured committed-entries/sec divided by the north-star rate
(1M entries / 60 s = 16667 entries/s).
"""

from __future__ import annotations

import json
import math
import os
import sys
import time
import traceback


def log(*a):  # all progress goes to stderr; stdout carries only the JSON line
    print(*a, file=sys.stderr, flush=True)


RESULT: dict = {
    "metric": "committed-log-entries/sec @ N simulated managers",
    "value": 0.0,
    "unit": "entries/s",
    "vs_baseline": 0.0,
}
BASELINE_RATE = 1_000_000 / 60.0


def _dump_metrics() -> None:
    """BENCH_METRICS_OUT=<path>: write the merged metrics snapshot (typed
    registry + legacy timers) as JSON next to the BENCH_*.json line, so a
    bench run leaves the same introspection data a live manager scrape
    serves. Best-effort — a metrics failure must never cost the bench
    number."""
    path = os.environ.get("BENCH_METRICS_OUT", "")
    if not path:
        return
    try:
        from swarmkit_tpu.metrics import exposition
        from swarmkit_tpu.metrics import registry as obs_registry
        from swarmkit_tpu.utils import metrics as legacy
        with open(path, "w") as f:
            json.dump(exposition.snapshot_all(
                registry=obs_registry.DEFAULT,
                legacy_registry=legacy.REGISTRY), f,
                indent=2, sort_keys=True, default=str)
    except Exception as e:
        log(f"metrics dump failed: {e}")


def _emit(error: str | None = None, hard: bool = False) -> None:
    """Single exit point: print the one JSON line and leave. A run whose
    headline number already exists stays a success even if an error arrives
    later (e.g. SIGTERM during the secondary configs)."""
    _dump_metrics()
    if error is not None:
        if RESULT.get("value"):
            RESULT.setdefault("note", error)
        else:
            RESULT.setdefault("error", error)
    print(json.dumps(RESULT), flush=True)
    code = 1 if "error" in RESULT else 0
    if hard:
        os._exit(code)
    sys.exit(code)


def emit_and_exit() -> None:
    _emit()


def _install_signal_handlers() -> None:
    """The driver kills over-budget benches with SIGTERM; emit the JSON line
    (with whatever partial results exist) before dying so the gate still
    records a parseable result."""
    import signal

    def _die(signum, frame):
        _emit(error=f"killed by signal {signum}", hard=True)

    signal.signal(signal.SIGTERM, _die)
    signal.signal(signal.SIGINT, _die)


# --- stall watchdog ---------------------------------------------------------
# A device call through the axon tunnel can hang forever inside the PJRT
# client (observed r03-r05: ~25 min at 0% CPU). Signal handlers can't help:
# they only run on the main thread, which is parked inside the C++ call — a
# SIGTERM is simply never delivered to Python (verified r05: the handler
# above produced nothing and the process needed SIGKILL, losing the JSON
# line). A daemon THREAD still runs (blocking PJRT calls release the GIL),
# so it can flush the partial results and hard-exit.
_last_progress = [0.0]


def _pet_watchdog() -> None:
    _last_progress[0] = time.monotonic()


def _start_watchdog() -> None:
    import threading

    stall_s = float(os.environ.get("BENCH_STALL_TIMEOUT_S", "600"))
    _pet_watchdog()

    def run():
        while True:
            time.sleep(15)
            idle = time.monotonic() - _last_progress[0]
            if idle > stall_s:
                _emit(error=f"no progress for {idle:.0f}s "
                            "(wedged device call?)", hard=True)

    threading.Thread(target=run, daemon=True).start()


def init_backend():
    """Initialize the JAX backend, probing first in a SUBPROCESS with a hard
    timeout — backend init can hang inside C++ (not raise) when the TPU
    tunnel is down, and a hang in-process would kill the whole bench. If the
    probe fails twice, pin CPU before importing jax here so a number is
    always produced."""
    import subprocess

    init_timeout = int(os.environ.get("BENCH_INIT_TIMEOUT_S", "120"))
    probe = "import jax; d = jax.devices(); print(d[0].platform, len(d))"
    platform = None
    for attempt in (1, 2):
        try:
            out = subprocess.run(
                [sys.executable, "-c", probe], capture_output=True,
                text=True, timeout=init_timeout)
            if out.returncode == 0:
                platform = out.stdout.split()[0]
                break
            log(f"backend probe attempt {attempt} rc={out.returncode}: "
                f"{out.stderr.strip()[-300:]}")
        except subprocess.TimeoutExpired:
            log(f"backend probe attempt {attempt} timed out "
                f"({init_timeout}s)")
        finally:
            # probing has its own timeout discipline; a long
            # BENCH_INIT_TIMEOUT_S must not trip the stall watchdog and
            # kill a run that would have fallen back to CPU
            _pet_watchdog()
        if attempt == 1:
            time.sleep(10)

    if platform is None:
        os.environ["JAX_PLATFORMS"] = "cpu"
        platform = "cpu-fallback"

    import threading

    import jax

    if platform == "cpu-fallback":
        jax.config.update("jax_platforms", "cpu")
        devs = jax.devices("cpu")
        return jax, devs, platform

    # The probe succeeded, but the tunnel can flap between probe and the real
    # init (TOCTOU) and the hang is inside C++ — a watchdog thread emits the
    # JSON line and hard-exits if init doesn't finish in time.
    done = threading.Event()

    def watchdog():
        if not done.wait(init_timeout + 30):
            _emit(error="in-process backend init hung after probe ok",
                  hard=True)

    threading.Thread(target=watchdog, daemon=True).start()
    try:
        devs = jax.devices()
    finally:
        done.set()
        _pet_watchdog()
    return jax, devs, platform


def election_tick_for(n: int) -> int:
    """Randomized election timeouts live in [T, 2T); with thousands of rows
    a 10-tick window guarantees candidate collisions, so widen with log2(N)
    (reference keeps T=10 because real clusters have <=7 managers,
    raft.go:484-488)."""
    return max(10, round(2 * math.log2(max(n, 2))))


class MeasureError(Exception):
    pass


def _telemetry_probe(jax, cfg, election_tick: int, shard_fn):
    """Short telemetry-enabled run on the measured shape: fresh state, enough
    ticks to elect and fill the latency histograms, then a TelemetryObs
    scrape into a private registry.  Runs SEPARATE from the timed loops so
    the histogram plumbing never perturbs the headline number (its on-path
    cost is the PERF.md A/B, not a bench tax); the small tick count bounds
    the extra compile.  BENCH_TELEMETRY=0 skips it entirely."""
    from dataclasses import replace

    from swarmkit_tpu.metrics.registry import MetricsRegistry
    from swarmkit_tpu.raft.sim import init_state, run_ticks
    from swarmkit_tpu.telemetry import TelemetryObs

    tcfg = replace(cfg, collect_telemetry=True)
    ticks = max(4 * election_tick, 64)
    st = shard_fn(init_state(tcfg))
    st, _ = run_ticks(st, tcfg, ticks, prop_count=min(64, tcfg.max_props))
    jax.block_until_ready(st.commit)
    _pet_watchdog()
    return TelemetryObs(registry=MetricsRegistry()).publish(st, tcfg)


def measure(jax, n: int, entries: int, seed: int, election_tick: int,
            latency: int = 0, latency_jitter: int = 0, inflight: int = 1,
            log_len: int = 8192, window: int = 2048, read_batch: int = 0,
            read_leases: bool = True, peer_chunk: int | None = None,
            active_rows: int | None = None, shard: bool = False,
            fsync_lag_ticks: int = 0, ack_gating: bool = False, **run_kw):
    """Elect a leader, then time one compiled steady-state replication run of
    ~`entries` committed entries. Returns a dict of measurements; raises
    MeasureError if no leader emerges.

    The steady-state scan is CHUNKED (BENCH_CHUNK_TICKS, default 64): each
    chunk is one on-device `lax.scan`, with a host sync between chunks. This
    bounds the runtime of any single XLA program execution — the r02 failure
    mode was one ~19-minute 489-tick scan being killed by the device runtime
    as "UNAVAILABLE: TPU device error" — while keeping >98% of the work on
    device. One chunk shape means one compile.

    Used identically by the headline bench and the secondary BASELINE
    configs so both measure the same flow.
    """
    from swarmkit_tpu.raft.sim import (
        SimConfig, committed_entries, has_leader, init_state, reads_blocked,
        reads_served, run_ticks, run_until_leader,
    )
    from swarmkit_tpu.raft.sim.run import KernelObs

    obs = KernelObs()
    # static_members: every bench config runs a fixed quorum (crashes and
    # drops are liveness faults, not membership changes), so the kernel's
    # static-membership specialization applies — the dynamic path is gated
    # by the differential suite and test_static_members_equivalence.
    # collect_stats: four O(N) reduces per tick against O(N^2) phases —
    # negligible, but BENCH_COLLECT_STATS=0 restores the bare program.
    # BENCH_RECORD_EVENTS=1 turns the flight recorder on, measuring the
    # masked-scatter overhead of event capture (PERF.md A/B).
    # BENCH_COLLECT_TELEMETRY=1 puts the telemetry plane ON the timed
    # path (stamps + histogram folds + series ring), the PERF.md
    # telemetry A/B; the default keeps the headline bare and measures
    # latency via the separate post-run probe instead.
    cfg = SimConfig(n=n, log_len=log_len, window=window, apply_batch=2048,
                    max_props=2048, keep=500, seed=seed,
                    election_tick=election_tick,
                    latency=latency, latency_jitter=latency_jitter,
                    inflight=inflight, static_members=True,
                    read_batch=read_batch, read_leases=read_leases,
                    collect_stats=os.environ.get(
                        "BENCH_COLLECT_STATS", "1") != "0",
                    record_events=os.environ.get(
                        "BENCH_RECORD_EVENTS", "0") == "1",
                    collect_telemetry=os.environ.get(
                        "BENCH_COLLECT_TELEMETRY", "0") == "1",
                    # peer_chunk picks the peer-axis lowering: None keeps
                    # the SimConfig default (banded hierarchical quorum
                    # reductions once n > peer_chunk), 0 pins the dense
                    # [N, N] tallies (the densepeer tripwire's reference)
                    **({} if peer_chunk is None
                       else {"peer_chunk": peer_chunk}),
                    # active_rows picks the progress lowering: None keeps
                    # the SimConfig default ([A, N] role-sparse slabs), 0
                    # pins the dense elementwise per-peer writes (the
                    # sparseprog tripwire's reference)
                    **({} if active_rows is None
                       else {"active_rows": active_rows}),
                    # fsync_lag_ticks arms the per-row storage model (the
                    # durability boundary); 0 keeps the storage-off
                    # config literally identical to the pre-storage bench
                    **({} if fsync_lag_ticks == 0
                       else {"fsync_lag_ticks": fsync_lag_ticks,
                             "ack_gating": ack_gating}))
    # shard=True runs the whole flow row-sharded over the device mesh
    # (32768-sharded config): with the banded peer reductions the kernel
    # never materializes a full [N, N] intermediate, so each device only
    # holds its row slab plus one [rows/D, peer_chunk] band at a time.
    if shard:
        from swarmkit_tpu.parallel import row_mesh, shard_rows
        _mesh = row_mesh(n)
        _shard = lambda st: shard_rows(st, _mesh)  # noqa: E731
    else:
        _shard = lambda st: st  # noqa: E731
    ticks_needed = max(1, (entries + cfg.max_props - 1) // cfg.max_props)
    chunk = int(os.environ.get("BENCH_CHUNK_TICKS", "64"))
    n_chunks = (ticks_needed + chunk - 1) // chunk

    def run_chunks(state):
        for _ in range(n_chunks):
            with obs.timed("run_ticks"):
                state, _ = run_ticks(state, cfg, chunk,
                                     prop_count=cfg.max_props, **run_kw)
                jax.block_until_ready(state.commit)
            _pet_watchdog()
        return state

    # Election is chunked for the same single-program-runtime reason.
    max_elect_ticks = 2000
    elect_chunk = 256

    def measure_election():
        """Run one election from fresh state; returns (state, ticks,
        seconds).  Raises if no leader emerges within the tick budget."""
        st = _shard(init_state(cfg))
        t0 = time.perf_counter()
        ticks = 0
        while ticks < max_elect_ticks:
            with obs.timed("run_until_leader"):
                st, t_chunk = run_until_leader(st, cfg, max_ticks=elect_chunk)
                jax.block_until_ready(st.term)
            _pet_watchdog()
            ticks += int(t_chunk)
            if bool(has_leader(st)):
                break
        if not bool(has_leader(st)):
            raise MeasureError(
                f"no leader elected within {max_elect_ticks} ticks "
                f"(n={n}, T={election_tick})")
        return st, ticks, time.perf_counter() - t0

    state, ticks, t_elect = measure_election()

    t0 = time.perf_counter()
    warm = run_chunks(state)
    t_compile = time.perf_counter() - t0
    del warm

    # Post-compile election latency: the first election above paid the
    # run_until_leader compile; re-running it from a fresh state (same
    # shapes, same seed, so the same trajectory) isolates PROTOCOL time —
    # published separately so the headline never conflates
    # compile-amortization with election speed.
    _, _, t_elect_post = measure_election()

    base = int(committed_entries(state))
    base_reads = int(reads_served(state)) if read_batch else 0
    t0 = time.perf_counter()
    final = run_chunks(state)
    dt = time.perf_counter() - t0
    committed = int(committed_entries(final)) - base

    out = {
        "cfg": cfg, "final": final, "committed": committed, "dt": dt,
        "rate": committed / dt, "election_ticks": ticks,
        "t_elect": t_elect, "t_elect_post": t_elect_post,
        "t_compile": t_compile, "kernel_stats": obs.publish(final),
    }
    if read_batch:
        reads = int(reads_served(final)) - base_reads
        out["reads"] = reads
        out["read_rate"] = reads / dt
        out["reads_blocked"] = int(reads_blocked(final))
    if os.environ.get("BENCH_TELEMETRY", "1") != "0":
        try:  # best-effort: latency numbers must never cost the bench number
            with obs.timed("telemetry_probe"):
                out["telemetry"] = _telemetry_probe(
                    jax, cfg, election_tick, _shard)
        except Exception as e:
            log(f"telemetry probe failed (n={n}): {type(e).__name__}: "
                f"{str(e)[:200]}")
    return out


def measure_multiraft(jax, groups: int, n: int, entries: int, seed: int,
                      collect_telemetry: bool = False):
    """Aggregate throughput of the [G, N] multi-raft serving plane.

    Elect leaders across all G groups (staggered timeouts), then time
    chunked scans of fused-propose ticks; the headline quantities are
    AGGREGATE committed entries/s and lease-served reads/s summed over
    groups — the many-small-groups serving story (G=1024 x N=3) vs the
    one-giant-group headline.  Groups shard over the device mesh via
    parallel.group_mesh when several devices are present.  Small per-group
    shapes keep this measurable on CPU at full G, so the config is never
    reduced.
    """
    from swarmkit_tpu import multiraft, parallel
    from swarmkit_tpu.raft.sim import SimConfig

    # telemetry side: per-group commit latency at this shape is tick-scale,
    # so the 64-deep batch ring covers every populatable bucket while
    # keeping the per-tick fold proportional to the tiny per-group kernel
    # (state.py telemetry_prop_ring: the fleet-scale telemetry cost lever)
    cfg = SimConfig(n=n, log_len=512, window=128, apply_batch=64,
                    max_props=32, keep=64, seed=seed, election_tick=10,
                    read_batch=32, read_leases=True, static_members=True,
                    collect_telemetry=collect_telemetry,
                    telemetry_prop_ring=64 if collect_telemetry else 0,
                    collect_stats=os.environ.get(
                        "BENCH_COLLECT_STATS", "1") != "0")
    gstate = multiraft.init_groups(cfg, groups)
    if len(jax.devices()) > 1:
        mesh = parallel.group_mesh(groups)
        gstate = parallel.shard_rows(gstate, mesh,
                                     axis=parallel.GROUP_AXIS,
                                     leading=groups)

    # Election phase: staggered initial timeouts put every group's first
    # campaign inside [T, 2T), so a couple of scan chunks settle the fleet;
    # require 99% with leaders (laggards elect during the timed run).
    elect_ticks = 0
    t0 = time.perf_counter()
    for _ in range(16):
        gstate, _ = multiraft.run_group_ticks(gstate, cfg, 32)
        jax.block_until_ready(gstate.commit)
        _pet_watchdog()
        elect_ticks += 32
        if int(multiraft.groups_with_leader(gstate)) >= groups * 99 // 100:
            break
    t_elect = time.perf_counter() - t0
    with_leader = int(multiraft.groups_with_leader(gstate))
    if with_leader < groups // 2 + 1:
        raise MeasureError(
            f"multiraft: only {with_leader}/{groups} groups elected a "
            f"leader within {elect_ticks} ticks")

    per_tick = groups * cfg.max_props
    ticks_needed = max(100, (entries + per_tick - 1) // per_tick)
    chunk = min(int(os.environ.get("BENCH_CHUNK_TICKS", "64")), 256)
    n_chunks = (ticks_needed + chunk - 1) // chunk

    def run_chunks(st):
        for _ in range(n_chunks):
            st, _ = multiraft.run_group_ticks(st, cfg, chunk,
                                              prop_count=cfg.max_props)
            jax.block_until_ready(st.commit)
            _pet_watchdog()
        return st

    t0 = time.perf_counter()
    warm = run_chunks(gstate)
    t_compile = time.perf_counter() - t0
    base = int(multiraft.aggregate_committed(warm))
    base_reads = int(multiraft.aggregate_reads_served(warm))
    t0 = time.perf_counter()
    final = run_chunks(warm)
    dt = time.perf_counter() - t0
    committed = int(multiraft.aggregate_committed(final)) - base
    reads = int(multiraft.aggregate_reads_served(final)) - base_reads
    obs = multiraft.MultiRaftObs()
    summary = obs.publish(final)
    return {"rate": committed / dt, "read_rate": reads / dt, "dt": dt,
            "committed": committed, "reads": reads, "groups": groups,
            "groups_with_leader": summary["groups_with_leader"],
            "elect_ticks": elect_ticks, "t_elect": t_elect,
            "t_compile": t_compile}


def _peak_bytes(jax) -> int | None:
    """Peak device-memory high-water mark across local devices, or None
    when the backend doesn't report one (CPU returns None or an empty
    stats dict — never fabricate a 0 that bench_gate would gate on)."""
    try:
        peaks = []
        for d in jax.local_devices():
            stats = d.memory_stats()
            if stats and stats.get("peak_bytes_in_use"):
                peaks.append(int(stats["peak_bytes_in_use"]))
        return max(peaks) if peaks else None
    except Exception:
        return None


def _bench_gauges(config: str, m: dict) -> None:
    """Fold one measure() result into the swarm_bench_* gauge families
    (best-effort: gauges must never cost the bench number)."""
    try:
        from swarmkit_tpu.metrics import catalog as obs_catalog
        from swarmkit_tpu.metrics import registry as obs_registry
        r = obs_registry.DEFAULT
        obs_catalog.get(r, "swarm_bench_entries_per_second").labels(
            config=config).set(m["rate"])
        obs_catalog.get(r, "swarm_bench_compile_seconds").labels(
            config=config).set(m["t_compile"])
        obs_catalog.get(r, "swarm_bench_election_seconds").labels(
            config=config).set(m["t_elect_post"])
        obs_catalog.get(r, "swarm_bench_election_ticks").labels(
            config=config).set(m["election_ticks"])
        if "read_rate" in m:
            obs_catalog.get(r, "swarm_bench_reads_per_second").labels(
                config=config).set(m["read_rate"])
        commit = (m.get("telemetry") or {}).get("commit") or {}
        for q, gauge in (("p50", "swarm_bench_commit_latency_ticks_p50"),
                         ("p99", "swarm_bench_commit_latency_ticks_p99")):
            if commit.get(q) is not None:
                obs_catalog.get(r, gauge).labels(config=config).set(commit[q])
    except Exception as e:
        log(f"bench gauges failed: {e}")


def _telemetry_json(m: dict) -> dict | None:
    """Per-config telemetry excerpt for the JSON line (None if the probe
    was skipped or produced no commits)."""
    tel = m.get("telemetry") or {}
    if not tel.get("enabled"):
        return None
    out = {"election_ticks": m["election_ticks"]}
    for q in ("p50", "p99"):
        out[f"commit_latency_ticks_{q}"] = (tel.get("commit") or {}).get(q)
    return out


def main() -> None:
    # `python bench.py 32768-sharded` == BENCH_ONLY_CONFIG=32768-sharded,
    # plus a tiny headline so the budget goes to the named config — the
    # invocation ROADMAP item 1 asks the driver to run.  An only-config
    # run that records no number for its config EXITS NONZERO (below), so
    # a green round always carries the entries/s tail it claims.
    if len(sys.argv) > 1 and not sys.argv[1].startswith("-"):
        os.environ.setdefault("BENCH_ONLY_CONFIG", sys.argv[1])
    only_cfg = os.environ.get("BENCH_ONLY_CONFIG", "")
    if only_cfg:
        RESULT["only_config"] = only_cfg
        os.environ.setdefault("BENCH_N", "64")
        os.environ.setdefault("BENCH_ENTRIES", "20000")
    n = int(os.environ.get("BENCH_N", "4096"))
    target_entries = int(os.environ.get("BENCH_ENTRIES", "1000000"))
    budget_s = float(os.environ.get("BENCH_BUDGET_S", "600"))
    t_start = time.perf_counter()

    _install_signal_handlers()
    _start_watchdog()
    jax, devices, platform = init_backend()
    import numpy as np

    RESULT["platform"] = platform
    on_cpu = platform in ("cpu", "cpu-fallback")
    if on_cpu:
        # A single CPU device cannot finish the n=4096 / 1M-entry north-star
        # run inside any driver budget ([N,N] progress is O(N^2) per tick);
        # shrink so a real number is still produced and flagged as reduced.
        if "BENCH_N" not in os.environ:
            n = 256
            RESULT["reduced_for_cpu"] = True
        if "BENCH_ENTRIES" not in os.environ:
            target_entries = 100_000
            RESULT["reduced_for_cpu"] = True
    log(f"devices: {devices}  n={n}")

    election_tick = int(os.environ.get(
        "BENCH_ELECTION_TICK", election_tick_for(n)))

    # Reduced-scale retry ladder: a mid-run device fault at the headline
    # scale must still produce SOME nonzero on-device number (r02 recorded
    # 0.0 because the only fallback was at backend-init time). A faulted
    # PJRT client usually stays wedged, so the backend is torn down and
    # rebuilt between rungs; the last rung runs on CPU.
    ladder = [("dev", n, target_entries)]
    if "BENCH_N" not in os.environ and not on_cpu:
        ladder += [("dev", 1024, 250_000), ("dev", 256, 100_000),
                   ("cpu", 256, 100_000)]

    def _rebuild_backend(pin_cpu: bool) -> None:
        import jax.extend.backend
        if pin_cpu:
            jax.config.update("jax_platforms", "cpu")
        jax.extend.backend.clear_backends()
        jax.devices()

    m = None
    for attempt, (plat, ln, lentries) in enumerate(ladder):
        try:
            if attempt > 0:
                _rebuild_backend(pin_cpu=(plat == "cpu"))
            m = measure(jax, ln, lentries, seed=42,
                        election_tick=int(os.environ.get(
                            "BENCH_ELECTION_TICK", election_tick_for(ln))))
            n = ln
            if attempt > 0:
                RESULT["reduced_after_fault"] = f"n={ln} on {plat}"
                if plat == "cpu":
                    RESULT["platform"] = "cpu-after-fault"
                    on_cpu = True  # keep secondary configs CPU-sized
            break
        except MeasureError as e:
            RESULT.setdefault("errors", []).append(str(e))
            log(f"measure failed at n={ln}: {e}")
        except Exception as e:  # device fault mid-run: retry smaller
            RESULT.setdefault("errors", []).append(
                f"n={ln}: {type(e).__name__}: {str(e)[:200]}")
            log(f"device fault at n={ln}: {type(e).__name__}: "
                f"{str(e)[:300]}")
    if m is None:
        RESULT["error"] = "all bench scales failed"
        emit_and_exit()
        return

    _bench_gauges(f"headline-n{n}", m)
    if m.get("kernel_stats"):
        RESULT["kernel_stats"] = m["kernel_stats"]
    RESULT["election_ticks"] = m["election_ticks"]
    RESULT["election_s_incl_compile"] = round(m["t_elect"], 2)
    RESULT["election_s_post_compile"] = round(m["t_elect_post"], 3)
    # Resource series for bench_gate (gated in the growth direction:
    # compile blow-ups and memory blow-ups are regressions too)
    RESULT["compile_seconds"] = round(m["t_compile"], 2)
    pb = _peak_bytes(jax)
    if pb is not None:
        RESULT["peak_bytes"] = pb
    tel = _telemetry_json(m)
    if tel is not None:
        RESULT["commit_latency_ticks_p50"] = tel["commit_latency_ticks_p50"]
        RESULT["commit_latency_ticks_p99"] = tel["commit_latency_ticks_p99"]
    log(f"leader elected in {m['election_ticks']} ticks "
        f"({m['t_elect']:.2f}s incl compile, {m['t_elect_post']:.3f}s "
        f"post-compile), election_tick={election_tick}; "
        f"compile pass {m['t_compile']:.2f}s")

    final, cfg = m["final"], m["cfg"]
    commit = np.asarray(final.commit)
    applied = np.asarray(final.applied)
    chk = np.asarray(final.apply_chk)
    # safety verification: equal applied => equal state-machine checksum
    by: dict = {}
    safety_ok = True
    for a, c in zip(applied.tolist(), chk.tolist()):
        if by.setdefault(a, c) != c:
            safety_ok = False
            log(f"SAFETY VIOLATION: checksum divergence at applied={a}")
    n_quorum = int((commit >= commit.max() - cfg.max_props).sum())
    RESULT["safety_ok"] = safety_ok
    RESULT["replicas_near_tip"] = n_quorum
    if not safety_ok:
        RESULT["error"] = "state-machine checksum divergence"
    elif n_quorum < n // 2 + 1:
        RESULT["error"] = f"only {n_quorum}/{n} replicas near commit tip"

    log(f"committed {m['committed']} entries across {n} managers in "
        f"{m['dt']:.2f}s ({m['rate']:,.0f} entries/s); total wall incl "
        f"election {m['dt'] + m['t_elect']:.2f}s")

    RESULT.update({
        "metric": f"committed-log-entries/sec @ {n} simulated managers "
                  f"(election {m['election_ticks']} ticks in "
                  f"{m['t_elect']:.2f}s)",
        "value": round(m["rate"], 1),
        "vs_baseline": round(m["rate"] / BASELINE_RATE, 3),
    })
    # Free the headline state before the secondary configs allocate theirs
    # (at n=4096 it holds ~550MB of log + progress arrays).
    del m, final, commit, applied, chk

    # --- BASELINE.json configs 3-5 (logged, secondary) ----------------------
    if os.environ.get("BENCH_CONFIGS", "1") != "0":
        # BENCH_ONLY_CONFIG=<substring> runs just the matching secondary
        # config — lets a narrow tunnel window capture one missing number
        # (pair with a tiny BENCH_N/BENCH_ENTRIES headline).
        only = os.environ.get("BENCH_ONLY_CONFIG", "")
        extra: dict = {}
        RESULT["configs_entries_per_s"] = extra  # by reference: partial
        # results survive a SIGTERM mid-loop
        tel_extra: dict = {}
        RESULT["configs_telemetry"] = tel_extra  # same by-reference rule
        for name, cn, kw in (
            ("64-steady", 64, {}),
            ("1024-crash-every-100", 1024, {"crash_every": 100, "down_for": 5}),
            ("4096-drop-5pct", 4096, {"drop_rate": 0.05}),
            # device-mailbox wire: per-edge latency 2 + jitter 1 with a
            # 4-deep pipelined append window (vendor MaxInflightMsgs)
            ("1024-mailbox-lat2-jitter1-inflight4", 1024,
             {"latency": 2, "latency_jitter": 1, "inflight": 4}),
            # log-capacity tripwire for the chunked log axis: with tiling,
            # an 8x larger ring must land within ~2x of the L=8192
            # headline rate (the un-tiled kernel degrades ~8x here)
            ("4096-longlog-L65536", 4096, {"log_len": 65536}),
            # read-heavy mix, 99:1 offered reads:writes — 99 reads per
            # committed entry spread over the rows (99 * 2048 / 256 per
            # row per refill).  reads/s is the SECOND HEADLINE metric:
            # lease-valid leaders serve with zero extra collectives,
            # followers serve at applied index one stamp round behind, so
            # served reads/s must stay >= 10x committed entries/s.
            ("256-readmix-99to1", 256,
             {"read_batch": 99 * 2048 // 256}),
            # durability A/B (handled specially below): the SAME shape
            # storage-off and with the full storage model armed
            # (fsync_lag_ticks=4 + ack-gating); the pinned signal is the
            # gated/bare rate ratio — the fsync round is O(N) cursor
            # arithmetic and gating only re-clamps existing ack folds,
            # so the ratio collapsing below ~0.8x means the storage
            # plane leaked into a hot phase (PERF.md "Durability
            # boundary": expected within noise of 1.0x)
            ("256-fsyncgate", 256, {"_storage_ab": True}),
            # peer-lowering regression tripwire (handled specially below):
            # the SAME shape measured dense (peer_chunk=0, full [N, N]
            # tallies) and banded (hierarchical quorum reductions); the
            # pinned signal is the banded/dense rate ratio — n=1024 is the
            # wash point, so banded collapsing below ~0.7x dense means the
            # banded lowering regressed, and dense collapsing means the
            # fallback did
            ("1024-densepeer", 1024, {"_peer_ab": True}),
            # progress-lowering regression tripwire (handled specially
            # below): the SAME shape measured with dense elementwise
            # per-peer progress writes (active_rows=0) and with the
            # role-sparse [A, N] slab lowering (active_rows=16); the
            # pinned signal is the sparse/dense rate ratio — the sparse
            # tick skips the O(N^2) progress writes entirely in steady
            # state, so the ratio collapsing toward 1.0 means the slab
            # lowering regressed (or the fallback is firing every tick)
            ("4096-sparseprog", 4096, {"_sparse_ab": True}),
            # sharded headline rung: rows sharded over the device mesh
            # with banded peer reductions — no device ever materializes a
            # full [N, N] intermediate, only its row slab plus one
            # [rows/D, peer_chunk] band (the n=32768 scaling story)
            ("32768-sharded", 32768, {"shard": True, "peer_chunk": 1024}),
            # multi-raft serving plane: aggregate committed entries/s and
            # reads/s summed over G=1024 independent N=3 groups (vmapped
            # kernel, groups sharded over the mesh) — the many-small-
            # groups production shape vs the one-giant-group headline.
            # Tiny per-group shapes make full G measurable even on CPU,
            # so this config never carries a -reduced suffix; the reads
            # number lands as the separate "multiraft-1024x3-reads"
            # series (bench_gate gates both as throughput series).
            ("multiraft-1024x3", 3, {"_multiraft": 1024}),
            # grouped-telemetry overhead tripwire (handled specially
            # below): the SAME [G=256, N=3] fleet measured bare and with
            # per-group telemetry (latency histograms + series rings)
            # folding in-kernel every tick; the pinned signal is the
            # telemetry/bare aggregate-rate ratio (bench_gate gates it
            # via the _over_dense key) — the fleet health plane's
            # "grouped telemetry stays within box noise" claim lives here
            ("multiraft-telemetry", 3, {"_multiraft_tel_ab": 256}),
            # batched proposal pipeline A/B (handled specially below):
            # sequential ProposeValue appends vs 64 in flight through the
            # store's coalescing pipeline on the SAME 3-manager quorum;
            # the pinned signal is the batched/sequential proposals/s
            # ratio (bench_gate gates it via the _over_dense key) — the
            # PR's >=5x acceptance bar lives here
            ("cpl-batch64", 3, {"_cpl_ab": True}),
            # control-plane load harness: 10k simulated agent sessions
            # over real gRPC sockets (registration, heartbeats, a hot
            # subset consuming assignments + writing statuses back);
            # records assignments/s as the gated series, with sustained
            # agents and heartbeat-RTT p99 alongside
            ("controlplane-10k", 0, {"_loadharness": 10_000}),
        ):
            if only and only not in name:
                extra.setdefault(f"filtered-by-only:{only}",
                                 "skipped (BENCH_ONLY_CONFIG)")
                continue
            if on_cpu and cn > 256:
                if "mailbox" in name:
                    # the mailbox wire must produce a number on EVERY
                    # platform (it had never been measured anywhere):
                    # run it reduced rather than skip it
                    name = f"{name}-reduced-n64"
                    cn = 64
                elif "longlog" in name:
                    # same rule for the log-capacity tripwire: the
                    # tiled-vs-capacity scaling it guards is visible at
                    # any n, so shrink rather than lose the number
                    name = f"{name}-reduced-n256"
                    cn = 256
                elif "densepeer" in name:
                    # the dense-vs-banded ratio is measurable wherever
                    # banding is legal (peer_chunk scales with n below)
                    name = f"{name}-reduced-n256"
                    cn = 256
                elif "sparseprog" in name:
                    # the sparse-vs-dense progress ratio is measurable at
                    # any n comfortably above active_rows; n=1024 keeps
                    # the CPU A/B pair inside the budget
                    name = f"{name}-reduced-n1024"
                    cn = 1024
                elif "sharded" in name:
                    # ISSUE 7: the 32k sharded rung runs CPU-reduced on
                    # the 8-virtual-device mesh; the no-[N,N]-buffer
                    # property it exercises is pinned at full scale by
                    # test_compile_budget's sharded 32k lowering
                    name = f"{name}-reduced-n4096"
                    cn = 4096
                else:
                    extra[name] = "skipped (cpu)"
                    continue
            if time.perf_counter() - t_start > budget_s:
                log(f"budget exhausted; skipping config {name}")
                extra[name] = "skipped (budget)"
                continue
            try:
                if kw.pop("_cpl_ab", False):
                    # batched-proposal tripwire: the replicated store's
                    # sequential propose path vs the coalescing pipeline
                    # at depth 64 on one quorum shape
                    import asyncio as _aio

                    from swarmkit_tpu.cmd.swarm_bench import \
                        bench as _cpl_bench
                    props = int(os.environ.get("BENCH_CPL_PROPOSALS",
                                               "300"))
                    dm = _aio.run(_cpl_bench(0, 0, managers=cn,
                                             proposals=props))
                    bm = _aio.run(_cpl_bench(0, 0, managers=cn,
                                             proposals=max(600, 2 * props),
                                             batch=64))
                    ratio = bm["proposals_per_s"] / dm["proposals_per_s"]
                    try:
                        from swarmkit_tpu.metrics import \
                            catalog as obs_catalog
                        from swarmkit_tpu.metrics import \
                            registry as obs_registry
                        r = obs_registry.DEFAULT
                        for tag, mm_ in (("dense", dm), ("batch64", bm)):
                            obs_catalog.get(
                                r, "swarm_bench_proposals_per_second"
                            ).labels(config=f"{name}-{tag}").set(
                                mm_["proposals_per_s"])
                    except Exception as e:
                        log(f"bench gauges failed: {e}")
                    extra[name] = {
                        "dense": dm["proposals_per_s"],
                        "batch64": bm["proposals_per_s"],
                        "entries_per_proposal": bm["entries_per_proposal"],
                        "batched_over_dense": round(ratio, 3)}
                    log(f"config {name}: sequential "
                        f"{dm['proposals_per_s']:,.0f} vs batch-64 "
                        f"{bm['proposals_per_s']:,.0f} proposals/s "
                        f"({ratio:.2f}x, {bm['entries_per_proposal']:.1f} "
                        f"entries/proposal)")
                    if ratio < 2.0:
                        RESULT.setdefault(
                            "note", f"proposal-pipeline tripwire: batched "
                            f"rate {bm['proposals_per_s']:,.0f} < 2x "
                            f"sequential {dm['proposals_per_s']:,.0f}")
                    continue
                la = kw.pop("_loadharness", 0)
                if la:
                    import asyncio as _aio
                    import importlib.util as _ilu
                    spec = _ilu.spec_from_file_location(
                        "soak_controlplane", os.path.join(
                            os.path.dirname(os.path.abspath(__file__)),
                            "tools", "soak_controlplane.py"))
                    harness = _ilu.module_from_spec(spec)
                    spec.loader.exec_module(harness)
                    agents = int(os.environ.get("BENCH_CPL_AGENTS",
                                                str(la)))
                    lm = _aio.run(harness.load(
                        minutes=float(os.environ.get(
                            "BENCH_CPL_MINUTES", "1.0")),
                        agents=agents, report_every=30.0,
                        sustain_floor=0.98))
                    if "error" in lm:
                        raise MeasureError(lm["error"])
                    extra[name] = lm["assignments_per_s"]
                    extra[f"{name}-agents-sustained"] = \
                        lm["agents_sustained"]
                    RESULT["controlplane"] = {
                        k: lm[k] for k in (
                            "agents", "agents_sustained", "rtt_p50_ms",
                            "rtt_p99_ms", "heartbeats_per_s",
                            "assignments_per_s", "entries_per_proposal")}
                    log(f"config {name}: {lm['agents_sustained']}/"
                        f"{lm['agents']} agents sustained, "
                        f"{lm['assignments_per_s']:.1f} assignments/s, "
                        f"hb rtt p99 {lm['rtt_p99_ms']:.1f}ms, "
                        f"{lm['entries_per_proposal']:.1f} "
                        f"entries/proposal")
                    continue
                gcount = kw.pop("_multiraft", 0)
                if gcount:
                    mm = measure_multiraft(jax, gcount, cn, target_entries,
                                           seed=7)
                    extra[name] = round(mm["rate"], 1)
                    extra[f"{name}-reads"] = round(mm["read_rate"], 1)
                    try:
                        from swarmkit_tpu.metrics import \
                            catalog as obs_catalog
                        from swarmkit_tpu.metrics import \
                            registry as obs_registry
                        r = obs_registry.DEFAULT
                        obs_catalog.get(
                            r, "swarm_bench_entries_per_second").labels(
                                config=name).set(mm["rate"])
                        obs_catalog.get(
                            r, "swarm_bench_reads_per_second").labels(
                                config=name).set(mm["read_rate"])
                        obs_catalog.get(
                            r, "swarm_bench_compile_seconds").labels(
                                config=name).set(mm["t_compile"])
                    except Exception as e:
                        log(f"bench gauges failed: {e}")
                    log(f"config {name}: {mm['rate']:,.0f} aggregate "
                        f"entries/s + {mm['read_rate']:,.0f} reads/s "
                        f"across {mm['groups_with_leader']}/{mm['groups']} "
                        f"led groups (elected in {mm['elect_ticks']} "
                        f"ticks)")
                    continue
                tel_groups = kw.pop("_multiraft_tel_ab", 0)
                if tel_groups:
                    # grouped-telemetry overhead tripwire: one fleet
                    # shape, bare vs telemetry-on; the pinned signal is
                    # the telemetry/bare aggregate-rate ratio
                    dm = measure_multiraft(jax, tel_groups, cn,
                                           target_entries, seed=7)
                    tm = measure_multiraft(jax, tel_groups, cn,
                                           target_entries, seed=7,
                                           collect_telemetry=True)
                    ratio = tm["rate"] / dm["rate"]
                    try:
                        from swarmkit_tpu.metrics import \
                            catalog as obs_catalog
                        from swarmkit_tpu.metrics import \
                            registry as obs_registry
                        fam = obs_catalog.get(
                            obs_registry.DEFAULT,
                            "swarm_bench_entries_per_second")
                        fam.labels(config=f"{name}-dense").set(dm["rate"])
                        fam.labels(config=f"{name}-on").set(tm["rate"])
                    except Exception as e:
                        log(f"bench gauges failed: {e}")
                    extra[name] = {
                        "dense": round(dm["rate"], 1),
                        "telemetry": round(tm["rate"], 1),
                        "telemetry_over_dense": round(ratio, 3)}
                    log(f"config {name}: bare {dm['rate']:,.0f} vs "
                        f"telemetry {tm['rate']:,.0f} aggregate entries/s "
                        f"({ratio:.2f}x) across {tel_groups} groups")
                    if ratio < 0.8:
                        RESULT.setdefault(
                            "note", f"grouped-telemetry tripwire: "
                            f"telemetry rate {tm['rate']:,.0f} < 0.8x "
                            f"bare {dm['rate']:,.0f} at {name}")
                    continue
                if kw.pop("_peer_ab", False):
                    # densepeer tripwire: one shape, both peer lowerings;
                    # the pinned signal is the banded/dense rate ratio
                    pc = max(64, cn // 4)
                    dm = measure(jax, cn, target_entries, seed=7,
                                 election_tick=election_tick_for(cn),
                                 peer_chunk=0, **kw)
                    bm = measure(jax, cn, target_entries, seed=7,
                                 election_tick=election_tick_for(cn),
                                 peer_chunk=pc, **kw)
                    ratio = bm["rate"] / dm["rate"]
                    _bench_gauges(f"{name}-dense", dm)
                    _bench_gauges(f"{name}-banded-pc{pc}", bm)
                    bt = _telemetry_json(bm)
                    if bt is not None:
                        tel_extra[name] = bt
                    extra[name] = {
                        "dense": round(dm["rate"], 1),
                        f"banded_pc{pc}": round(bm["rate"], 1),
                        "banded_over_dense": round(ratio, 3)}
                    log(f"config {name}: dense {dm['rate']:,.0f} vs banded "
                        f"{bm['rate']:,.0f} entries/s ({ratio:.2f}x)")
                    if ratio < 0.7:
                        RESULT.setdefault(
                            "note", f"peer-tiling tripwire: banded rate "
                            f"{bm['rate']:,.0f} < 0.7x dense "
                            f"{dm['rate']:,.0f} at {name}")
                    continue
                if kw.pop("_storage_ab", False):
                    # fsyncgate tripwire: one shape, bare vs the armed
                    # storage model; the pinned signal is the gated/bare
                    # rate ratio (bench_gate tracks it as
                    # 256-fsyncgate:ratio via the _over_dense key).
                    # BOTH sides get an append window deep enough to
                    # cover the fsync pipeline (window > (k+1) *
                    # max_props): durable acks lag k ticks, and a
                    # window that cannot hold k rounds of in-flight
                    # entries throttles replication to window/k per
                    # tick — ~1/k of the bare rate, the
                    # under-provisioning cliff PERF.md documents —
                    # which would measure provisioning, not the
                    # storage model's compute cost
                    k = 4
                    depth = dict(log_len=32768, window=(k + 1) * 2048 + 512)
                    dm = measure(jax, cn, target_entries, seed=7,
                                 election_tick=election_tick_for(cn),
                                 **depth, **kw)
                    gm = measure(jax, cn, target_entries, seed=7,
                                 election_tick=election_tick_for(cn),
                                 fsync_lag_ticks=k, ack_gating=True,
                                 **depth, **kw)
                    ratio = gm["rate"] / dm["rate"]
                    _bench_gauges(f"{name}-dense", dm)
                    _bench_gauges(f"{name}-gated-k{k}", gm)
                    gt = _telemetry_json(gm)
                    if gt is not None:
                        tel_extra[name] = gt
                    extra[name] = {
                        "dense": round(dm["rate"], 1),
                        f"gated_k{k}": round(gm["rate"], 1),
                        "gated_over_dense": round(ratio, 3)}
                    log(f"config {name}: bare {dm['rate']:,.0f} vs gated "
                        f"{gm['rate']:,.0f} entries/s ({ratio:.2f}x)")
                    if ratio < 0.8:
                        RESULT.setdefault(
                            "note", f"storage tripwire: gated rate "
                            f"{gm['rate']:,.0f} < 0.8x bare "
                            f"{dm['rate']:,.0f} at {name}")
                    continue
                if kw.pop("_sparse_ab", False):
                    # sparseprog tripwire: one shape, both progress
                    # lowerings; the pinned signal is the sparse/dense
                    # rate ratio (steady state, so the slab path should
                    # win outright — see PERF.md "Role-sparse progress")
                    ar = 16
                    dm = measure(jax, cn, target_entries, seed=7,
                                 election_tick=election_tick_for(cn),
                                 active_rows=0, **kw)
                    sm = measure(jax, cn, target_entries, seed=7,
                                 election_tick=election_tick_for(cn),
                                 active_rows=ar, **kw)
                    ratio = sm["rate"] / dm["rate"]
                    _bench_gauges(f"{name}-dense", dm)
                    _bench_gauges(f"{name}-sparse-a{ar}", sm)
                    st_tel = _telemetry_json(sm)
                    if st_tel is not None:
                        tel_extra[name] = st_tel
                    extra[name] = {
                        "dense": round(dm["rate"], 1),
                        f"sparse_a{ar}": round(sm["rate"], 1),
                        "sparse_over_dense": round(ratio, 3)}
                    log(f"config {name}: dense {dm['rate']:,.0f} vs sparse "
                        f"{sm['rate']:,.0f} entries/s ({ratio:.2f}x)")
                    if ratio < 1.0:
                        RESULT.setdefault(
                            "note", f"sparse-progress tripwire: sparse "
                            f"rate {sm['rate']:,.0f} < dense "
                            f"{dm['rate']:,.0f} at {name}")
                    continue
                cm = measure(jax, cn, target_entries, seed=7,
                             election_tick=election_tick_for(cn), **kw)
                _bench_gauges(name, cm)
                extra[name] = round(cm["rate"], 1)
                ct = _telemetry_json(cm)
                if ct is not None:
                    tel_extra[name] = ct
                log(f"config {name}: {cm['rate']:,.0f} entries/s "
                    f"(election {cm['election_ticks']} ticks)")
                if "read_rate" in cm:
                    # second headline: linearizable reads served/sec
                    RESULT["read_metric"] = (
                        f"linearizable-reads/sec @ {cn} simulated managers "
                        f"(99:1 offered read:write mix)")
                    RESULT["reads_per_second"] = round(cm["read_rate"], 1)
                    RESULT["read_write_ratio"] = round(
                        cm["read_rate"] / cm["rate"], 1)
                    RESULT["reads_blocked"] = cm["reads_blocked"]
                    if cm["read_rate"] < 10 * cm["rate"]:
                        RESULT.setdefault(
                            "note", f"read-mix underperformed: "
                            f"{cm['read_rate']:,.0f} reads/s < 10x "
                            f"{cm['rate']:,.0f} entries/s")
                    log(f"config {name}: {cm['read_rate']:,.0f} reads/s "
                        f"({RESULT['read_write_ratio']}x entries/s, "
                        f"{cm['reads_blocked']} blocked)")
            except Exception as e:  # secondary configs must not kill the run
                log(f"config {name} failed: {e}")
                extra[name] = f"failed: {e}"

        if only:
            # An only-config invocation exists to capture ONE number; a
            # run that recorded none (skipped, failed, or name typo) must
            # not exit 0 — rc=0 with no entries/s tail is exactly the
            # green-but-empty trajectory bench_gate's provenance check
            # flags (MULTICHIP r02-r05).
            def _recorded(v):
                if isinstance(v, (int, float)) \
                        and not isinstance(v, bool) and v > 0:
                    return True
                return isinstance(v, dict) and any(
                    _recorded(x) for x in v.values())
            hits = {k: v for k, v in extra.items()
                    if only in k and not k.startswith("filtered-by-only:")}
            if not any(_recorded(v) for v in hits.values()):
                RESULT["error"] = (
                    f"only-config {only!r} recorded no rate "
                    f"({hits if hits else 'no matching config name'})")

    emit_and_exit()


if __name__ == "__main__":
    try:
        main()
    except SystemExit:
        raise
    except BaseException as e:  # always emit the JSON line
        traceback.print_exc(file=sys.stderr)
        RESULT["error"] = f"{type(e).__name__}: {e}"
        emit_and_exit()
